"""Summarize FlightRecorder JSONL dumps into a per-phase table.

Usage:
  python tools/telemetry_report.py RUN_DIR_OR_JSONL [more ...] [--json]
      [--by-worker]

Accepts recorder JSONL files and/or directories containing them (a
``--telemetry-dir`` run drops ``server.jsonl`` + ``worker-N.jsonl`` +
``trace.json`` in one directory; every ``*.jsonl`` inside is merged).
Spans aggregate into count / total / mean / p50 / p95 / max wall time
per name; point events are counted. ``--by-worker`` splits rows per
worker id — the straggler view. ``--json`` emits the same summary as a
machine-readable dict (what ``bench.py`` embeds).

Gradient-lineage files (``lineage-*.jsonl``, ``telemetry.lineage``) get
their own section — exact push-latency/staleness tables per worker,
per-version composition summary, critical-path stage counts — and are
routed AWAY from the recorder-span merge like the beacon/faults/numerics
side channels.

Prometheus scrape snapshots (``*.prom`` — ``serve()`` drops
``metrics.prom`` into the telemetry dir at exit) are parsed too,
INCLUDING worker-labeled series (``ps_frames_rejected_total{worker="1"}``,
``ps_worker_anomaly_total{...}`` — previously silently ignored): labeled
instruments are tabulated per worker in their own section.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_ps_mpi_tpu.telemetry import load_jsonl


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def collect_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            # faults-*.jsonl are injected-fault event logs (resilience
            # layer), beacon-*.jsonl are health-monitor side channels,
            # numerics-*.jsonl are codec-fidelity/grad-norm
            # trajectories, and lineage-*.jsonl are per-version push
            # compositions — none are recorder files (their rows have no
            # recorder name/kind), so they must not enter the span merge.
            # numerics-*.jsonl, lineage-*.jsonl and postmortem-*.json
            # ARE picked up here, routed to their own sections by
            # summarize().
            out.extend(sorted(
                f for f in glob.glob(os.path.join(p, "*.jsonl"))
                if not os.path.basename(f).startswith(
                    ("faults-", "beacon-"))
            ))
            out.extend(sorted(glob.glob(os.path.join(p, "*.prom"))))
            out.extend(sorted(glob.glob(
                os.path.join(p, "postmortem-*.json"))))
        else:
            out.append(p)
    if not out:
        raise SystemExit(f"no .jsonl/.prom files found under {paths}")
    return out


def parse_prometheus_text(text: str) -> List[Dict[str, Any]]:
    """Prometheus exposition text → ``[{name, labels, value}]`` rows
    (``# HELP``/``# TYPE`` skipped; label values unescaped enough for
    the simple labels this stack emits)."""
    import re

    series: List[Dict[str, Any]] = []
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)\s*$")
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = line_re.match(line)
        if not m:
            continue
        name, labels_text, raw = m.groups()
        try:
            value = float(raw.replace("+Inf", "inf"))
        except ValueError:
            continue
        labels = dict(label_re.findall(labels_text)) if labels_text else {}
        series.append({"name": name, "labels": labels, "value": value})
    return series


def _summarize_numerics(traj_rows: List[Dict[str, Any]],
                        probe_rows: List[Dict[str, Any]],
                        postmortems: List[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """The numerics section: grad-norm trajectory summary from the
    server rows, latest codec-fidelity probe per (worker, codec), and
    the postmortem dumps found in the directory."""
    if not (traj_rows or probe_rows or postmortems):
        return None
    out: Dict[str, Any] = {"postmortems": postmortems}
    norms = [r["grad_norm"] for r in traj_rows
             if isinstance(r.get("grad_norm"), (int, float))]
    if traj_rows:
        last = traj_rows[-1]
        out["trajectory"] = {
            "rows": len(traj_rows),
            "grad_norm_first": norms[0] if norms else None,
            "grad_norm_last": norms[-1] if norms else None,
            "grad_norm_min": min(norms) if norms else None,
            "grad_norm_max": max(norms) if norms else None,
            "update_ratio_last": last.get("update_ratio"),
            "nonfinite_total": last.get("nonfinite_total", 0),
        }
    latest: Dict[Any, Dict[str, Any]] = {}
    counts: Dict[Any, int] = {}
    for r in probe_rows:  # file order == append order: keep the latest
        k = (r.get("worker"), r.get("codec"))
        latest[k] = r
        counts[k] = counts.get(k, 0) + 1
    out["probes"] = [
        {"worker": k[0], "codec": k[1],
         "rel_error": v.get("rel_error"), "cosine": v.get("cosine"),
         "bits_per_param": v.get("bits_per_param"),
         "ef_residual_norm": v.get("ef_residual_norm"),
         "probes": counts[k]}
        for k, v in sorted(latest.items(), key=lambda kv: str(kv[0]))
    ]
    return out


def _summarize_lineage(rows: List[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """The lineage section: exact push-latency/staleness tables,
    per-version composition summary, and critical-path stage counts —
    aggregated from ``lineage-*.jsonl`` publish/drop/round rows."""
    if not rows:
        return None
    publishes = [r for r in rows if r.get("kind") == "publish"]
    drops = [r for r in rows if r.get("kind") == "drop"]
    rounds = [r for r in rows if r.get("kind") == "round"]
    per_worker: Dict[Any, Dict[str, List[float]]] = {}
    sizes: List[int] = []
    for r in publishes:
        pushes = r.get("pushes") or []
        sizes.append(len(pushes))
        for p in pushes:
            d = per_worker.setdefault(p.get("worker"),
                                      {"e2e": [], "stale": []})
            if p.get("e2e_s") is not None:
                d["e2e"].append(float(p["e2e_s"]))
            d["stale"].append(float(p.get("staleness", 0)))
    for r in drops:
        p = r.get("push") or {}
        d = per_worker.setdefault(p.get("worker"),
                                  {"e2e": [], "stale": []})
        if "staleness" in p:
            d["stale"].append(float(p["staleness"]))
    workers = []
    for w, d in sorted(per_worker.items(), key=lambda kv: str(kv[0])):
        e2e, stale = sorted(d["e2e"]), sorted(d["stale"])
        workers.append({
            "worker": w, "pushes": len(stale),
            "e2e_ms_p50": 1e3 * _percentile(e2e, 0.50) if e2e else None,
            "e2e_ms_p95": 1e3 * _percentile(e2e, 0.95) if e2e else None,
            "stale_p50": _percentile(stale, 0.50) if stale else None,
            "stale_max": stale[-1] if stale else None,
        })
    critical: Dict[Any, int] = {}
    for r in rounds:
        k = (r.get("gating_worker"), r.get("stage"))
        critical[k] = critical.get(k, 0) + 1
    return {
        "publishes": len(publishes),
        "pushes_composed": sum(sizes),
        "drops": len(drops),
        "composition": {
            "mean_pushes_per_version": (sum(sizes) / len(sizes)
                                        if sizes else 0.0),
            "max_pushes_per_version": max(sizes) if sizes else 0,
        },
        "workers": workers,
        "critical_path": [
            {"worker": w, "stage": s, "rounds": n}
            for (w, s), n in sorted(critical.items(),
                                    key=lambda kv: -kv[1])
        ],
    }


def summarize(files: List[str], by_worker: bool = False) -> Dict[str, Any]:
    """Merged summary over every file: per-span-name stats, event counts,
    and recorder meta (dropped counts make truncation visible)."""
    spans: Dict[Any, List[float]] = {}
    events: Dict[Any, int] = {}
    meta: List[Dict[str, Any]] = []
    labeled: List[Dict[str, Any]] = []
    traj_rows: List[Dict[str, Any]] = []
    probe_rows: List[Dict[str, Any]] = []
    postmortems: List[Dict[str, Any]] = []
    lineage_rows: List[Dict[str, Any]] = []
    for path in files:
        base = os.path.basename(path)
        if base.startswith("postmortem-") and path.endswith(".json"):
            # a divergence postmortem dump (telemetry.numerics) — one
            # JSON document, NOT an event JSONL; surface its headline
            try:
                with open(path) as f:
                    pm = json.load(f)
            except ValueError:
                continue
            postmortems.append({
                "file": base, "reason": pm.get("reason"),
                "worker": pm.get("worker"), "applied": pm.get("applied"),
                "ring_rows": len(pm.get("step_stats_ring") or []),
            })
            continue
        if base.startswith("lineage-") and path.endswith(".jsonl"):
            # per-version push compositions (telemetry.lineage) — routed
            # to the lineage section, never the recorder-span merge
            from pytorch_ps_mpi_tpu.telemetry.lineage import (
                load_lineage_rows,
            )

            lineage_rows.extend(load_lineage_rows(path))
            continue
        if base.startswith("numerics-") and path.endswith(".jsonl"):
            # numerics trajectories: the server's grad-norm/update-ratio
            # rows and the workers' codec-fidelity probe rows
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        r = json.loads(line)
                    except ValueError:
                        continue
                    (traj_rows if r.get("worker") == "server"
                     else probe_rows).append(r)
            continue
        if path.endswith(".prom"):
            with open(path) as f:
                for s in parse_prometheus_text(f.read()):
                    # the per-worker labeled series (PR 3's rejection
                    # counters, the diagnosis layer's anomaly/gating/
                    # health instruments) are the tabulation target;
                    # unlabeled totals already ride the metrics dicts
                    if s["labels"]:
                        labeled.append({"file": os.path.basename(path),
                                        **s})
            continue
        m, rows = load_jsonl(path)
        if m:
            meta.append({"file": os.path.basename(path),
                         "worker": m.get("worker"),
                         "n_events": m.get("n_events"),
                         "dropped": m.get("dropped", 0)})
        for r in rows:
            key = ((r["name"], r.get("worker")) if by_worker
                   else (r["name"], None))
            if r.get("kind") == "span":
                spans.setdefault(key, []).append(float(r.get("dur", 0.0)))
            else:
                events[key] = events.get(key, 0) + 1

    def row(key, durs):
        durs = sorted(durs)
        name, worker = key
        return {
            "name": name,
            "worker": worker,
            "count": len(durs),
            "total_s": sum(durs),
            "mean_ms": 1e3 * sum(durs) / len(durs),
            "p50_ms": 1e3 * _percentile(durs, 0.50),
            "p95_ms": 1e3 * _percentile(durs, 0.95),
            "max_ms": 1e3 * durs[-1],
        }

    return {
        "files": meta,
        "spans": sorted(
            (row(k, v) for k, v in spans.items()),
            key=lambda r: -r["total_s"],
        ),
        "events": [
            {"name": k[0], "worker": k[1], "count": n}
            for k, n in sorted(events.items(), key=lambda kv: -kv[1])
        ],
        # worker-labeled (and any other labeled) instrument series from
        # *.prom scrape snapshots, histogram bucket rows excluded (the
        # per-worker counters are the per-worker story)
        "labeled_metrics": sorted(
            (s for s in labeled if "le" not in s["labels"]),
            key=lambda s: (s["name"], sorted(s["labels"].items())),
        ),
        "numerics": _summarize_numerics(traj_rows, probe_rows, postmortems),
        "lineage": _summarize_lineage(lineage_rows),
        "dropped_total": sum(m.get("dropped") or 0 for m in meta),
    }


def format_table(summary: Dict[str, Any]) -> str:
    lines: List[str] = []
    has_worker = any(r["worker"] is not None for r in summary["spans"])
    cols = (["phase"] + (["worker"] if has_worker else [])
            + ["count", "total s", "mean ms", "p50 ms", "p95 ms", "max ms"])
    rows = []
    for r in summary["spans"]:
        row = [r["name"]] + ([str(r["worker"])] if has_worker else []) + [
            str(r["count"]), f"{r['total_s']:.3f}", f"{r['mean_ms']:.2f}",
            f"{r['p50_ms']:.2f}", f"{r['p95_ms']:.2f}", f"{r['max_ms']:.2f}",
        ]
        rows.append(row)
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    fmt = "  ".join(f"{{:<{w}}}" if i == 0 else f"{{:>{w}}}"
                    for i, w in enumerate(widths))
    lines.append(fmt.format(*cols))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append(fmt.format(*r))
    if summary["events"]:
        lines.append("")
        lines.append("events:")
        for e in summary["events"]:
            who = f" [worker {e['worker']}]" if e["worker"] is not None else ""
            lines.append(f"  {e['name']}{who}: {e['count']}")
    if summary.get("labeled_metrics"):
        lines.append("")
        lines.append("labeled metrics (scrape snapshot):")
        for s in summary["labeled_metrics"]:
            labels = ",".join(f"{k}={v}"
                              for k, v in sorted(s["labels"].items()))
            v = s["value"]
            v_txt = str(int(v)) if float(v).is_integer() else f"{v:.6g}"
            lines.append(f"  {s['name']}{{{labels}}}: {v_txt}")
    num = summary.get("numerics")
    if num:
        lines.append("")
        lines.append("numerics:")
        traj = num.get("trajectory")
        if traj:
            ur = traj.get("update_ratio_last")
            lines.append(
                f"  grad-norm trajectory ({traj['rows']} rows): "
                f"first={traj['grad_norm_first']:.4g} "
                f"last={traj['grad_norm_last']:.4g} "
                f"min={traj['grad_norm_min']:.4g} "
                f"max={traj['grad_norm_max']:.4g}"
                + (f"  update-ratio={ur:.3g}" if ur is not None else "")
            )
            lines.append(
                f"  nonfinite pushes: {int(traj.get('nonfinite_total', 0))}"
            )
        def _g(v, spec=".4g"):
            # a probe that landed on a poisoned gradient carries None
            return "-" if v is None else format(v, spec)

        for p in num.get("probes", []):
            ef = p.get("ef_residual_norm")
            lines.append(
                f"  codec fidelity [worker {p['worker']}] {p['codec']}: "
                f"rel-err={_g(p['rel_error'])} cos={_g(p['cosine'])} "
                f"bits/param={_g(p['bits_per_param'], '.3g')} "
                f"({p['probes']} probes)"
                + (f" ef-residual={ef:.4g}" if ef is not None else "")
            )
        for pm in num.get("postmortems", []):
            lines.append(
                f"  postmortem {pm['file']}: reason={pm['reason']} "
                f"worker={pm['worker']} applied={pm['applied']} "
                f"ring={pm['ring_rows']} rows"
            )
    lin = summary.get("lineage")
    if lin:
        lines.append("")
        lines.append("lineage:")
        comp = lin["composition"]
        lines.append(
            f"  {lin['publishes']} published versions composed of "
            f"{lin['pushes_composed']} pushes "
            f"(mean {comp['mean_pushes_per_version']:.2f}/version, "
            f"max {comp['max_pushes_per_version']}); "
            f"{lin['drops']} pushes dropped"
        )

        def _ms(v):
            return "-" if v is None else f"{v:.1f}ms"

        for w in lin.get("workers", []):
            stale50 = w.get("stale_p50")
            lines.append(
                f"  worker {w['worker']}: {w['pushes']} pushes  "
                f"e2e p50/p95={_ms(w.get('e2e_ms_p50'))}/"
                f"{_ms(w.get('e2e_ms_p95'))}  "
                f"stale p50/max="
                f"{'-' if stale50 is None else f'{stale50:.0f}'}/"
                f"{'-' if w.get('stale_max') is None else int(w['stale_max'])}"
            )
        for c in lin.get("critical_path", []):
            lines.append(
                f"  critical path: worker {c['worker']} "
                f"[{c['stage']}] gated {c['rounds']} rounds"
            )
    if summary["dropped_total"]:
        lines.append("")
        lines.append(
            f"WARNING: {summary['dropped_total']} records evicted by the "
            "bounded buffer — raise the recorder capacity for a complete log"
        )
    return "\n".join(lines)


def main(argv=None) -> Dict[str, Any]:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="recorder .jsonl files and/or directories of them")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--by-worker", action="store_true",
                    help="split span rows per worker id (straggler view)")
    args = ap.parse_args(argv)
    summary = summarize(collect_files(args.paths), by_worker=args.by_worker)
    if args.json:
        print(json.dumps(summary))
    else:
        print(format_table(summary))
    return summary


if __name__ == "__main__":
    main()
