"""Fit the scaling model's transport parameters to the MEASURED
multi-process DCN points (VERDICT r4 next #6).

The ring extrapolation (`benchmarks/results/cpu_scaling_resnet18_*.jsonl`,
`scaling_extrapolation_ring_model` row) anchored weak-scaling efficiency
to one measured TPU step time with link bandwidth as an ASSUMED
parameter. This script replaces assumption with fit wherever this host
actually measured transport:

1. **In-process collective bandwidth** — the 2/4/8-virtual-device rows
   measure `comm_ms_per_dev` against known `wire_bytes_per_worker`:
   fit one effective bandwidth `BW_eff` minimizing the relative residual
   of `comm_ms = wire_bytes / BW_eff`, and report per-point residuals
   (how well the model's linear-in-bytes structure holds).
2. **Per-boundary DCN cost** — the 8-worker runs at 1/2/4/8 processes
   measure the same program with every psum crossing 0/1/3/7 process
   boundaries: fit `T(p) = T_inproc + k * boundaries(p)` by least
   squares and report the residual — the model's
   linear-in-boundary-crossings structure, checked against data over a
   7x boundary range.

The ICI tier stays a labeled parameter (a single tunneled chip has no
ICI link to measure); what the fit buys is (a) the model's *structure*
validated on the two tiers this host can measure, and (b) the honest
magnitude gap between loopback-process transport and the assumed ICI.

Run: ``python tools/fit_scaling.py [--artifact PATH]`` — prints JSON
rows; append to ``benchmarks/results/`` and cite from docs/RESULTS.md.
"""

from __future__ import annotations

import argparse
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT = os.path.join(
    REPO, "benchmarks", "results", "cpu_scaling_resnet18_2026-07-31.jsonl"
)


def emit(**rec):
    print(json.dumps(rec), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifact", default=DEFAULT)
    args = ap.parse_args()

    rows = [json.loads(l) for l in open(args.artifact) if l.strip()]
    inproc = {r["workers"]: r for r in rows
              if r.get("processes") == 1 and "comm_ms_per_dev" in r}
    multi = {r["processes"]: r for r in rows
             if r.get("processes", 1) > 1 and r.get("workers") == 8}

    # -- 1. in-process collective bandwidth fit -------------------------
    pts = [(r["wire_bytes_per_worker"], r["comm_ms_per_dev"])
           for w, r in sorted(inproc.items()) if w > 1]
    # least squares on the RELATIVE error of comm_ms = bytes / BW:
    # minimize sum_i ((b_i * x - t_i) / t_i)^2 over x = 1/BW, whose
    # closed form is x = sum(b_i/t_i) / sum(b_i^2/t_i^2) — every point
    # weighs equally regardless of its absolute wall (an absolute-error
    # OLS would let the largest-byte point dominate and contradict the
    # per-point relative residuals reported below)
    num = sum(b / t for b, t in pts)
    den = sum((b * b) / (t * t) for b, t in pts)
    inv_bw = num / den                       # ms per byte
    bw_eff = 1.0 / inv_bw / 1e6              # bytes/ms -> GB/s-ish scale
    resid = [
        {"workers": w,
         "measured_comm_ms": r["comm_ms_per_dev"],
         "fit_comm_ms": round(r["wire_bytes_per_worker"] * inv_bw, 2),
         "rel_residual": round(
             (r["wire_bytes_per_worker"] * inv_bw - r["comm_ms_per_dev"])
             / r["comm_ms_per_dev"], 3)}
        for w, r in sorted(inproc.items()) if w > 1
    ]
    emit(
        metric="scaling_fit_inprocess_collective_bw",
        value=round(bw_eff, 3),
        unit="GB/s",
        model="comm_ms_per_dev = wire_bytes_per_worker / BW_eff",
        points=resid,
        note=(
            "effective XLA:CPU collective bandwidth on this host, fitted "
            "to the measured 2/4/8-device comm walls; the linear-in-bytes "
            "structure of the ring model is what the residuals check. "
            "Host-CPU magnitude — NOT an ICI estimate"
        ),
        artifact=os.path.basename(args.artifact),
    )

    # -- 2. per-boundary DCN (multi-process) cost fit --------------------
    if 1 not in {r.get("processes") for r in rows} or not multi:
        emit(metric="scaling_fit_boundary_cost", error="missing rows")
        return
    t1 = inproc[8]["step_ms"]
    # contiguous-block rings: p processes -> p-1 boundary chains crossed
    pts2 = [(p - 1, r["step_ms"] - t1) for p, r in sorted(multi.items())]
    # same relative-error objective as fit #1 (see comment there)
    k = (sum(b / dt for b, dt in pts2)
         / sum((b * b) / (dt * dt) for b, dt in pts2))
    resid2 = [
        {"processes": p,
         "boundaries": p - 1,
         "measured_extra_ms": round(r["step_ms"] - t1, 1),
         "fit_extra_ms": round(k * (p - 1), 1),
         "rel_residual": round(
             (k * (p - 1) - (r["step_ms"] - t1)) / (r["step_ms"] - t1), 3)}
        for p, r in sorted(multi.items())
    ]
    wire = inproc[8]["wire_bytes_per_worker"]
    emit(
        metric="scaling_fit_boundary_cost",
        value=round(k, 1),
        unit="ms/boundary",
        model="step_ms(p procs) = step_ms(in-proc) + k * (p - 1)",
        points=resid2,
        implied_boundary_gbytes_per_s=round(wire / k / 1e6, 4),
        note=(
            "per-process-boundary transport cost fitted to the measured "
            "2-, 4-, and 8-process coordinated runs (loopback gRPC + one "
            "shared kernel); the linear-in-boundaries structure is the "
            "checked claim. The implied boundary bandwidth is loopback-"
            "on-a-contended-host magnitude — it bounds the DCN tier's "
            "STRUCTURE, not a datacenter NIC's rate"
        ),
        artifact=os.path.basename(args.artifact),
    )

    # -- 3. re-anchored extrapolation: fitted-vs-assumed ----------------
    extrap = next((r for r in rows
                   if r.get("metric") == "scaling_extrapolation_ring_model"),
                  None)
    if extrap:
        t_c = extrap["t_compute_ms"]
        wire_b = extrap["wire_bytes"]

        def eff(w, bw_gbs):
            t_comm = 2 * (w - 1) / w * wire_b / (bw_gbs * 1e6)  # ms
            return t_c / (t_c + t_comm)

        assumed = extrap["ici_gbytes_per_s"]
        emit(
            metric="scaling_extrapolation_fitted_vs_assumed",
            t_compute_ms=t_c,
            wire_bytes=wire_b,
            assumed_ici_gbytes_per_s=assumed,
            predicted_efficiency_assumed={
                str(w): round(eff(w, assumed), 4) for w in (8, 64, 256)
            },
            fitted_host_collective_gbytes_per_s=round(bw_eff, 3),
            predicted_efficiency_if_links_were_host_grade={
                str(w): round(eff(w, bw_eff), 4) for w in (8, 64, 256)
            },
            note=(
                "the ring model's structure is now validated against both "
                "measured tiers (see the two fit rows); the ICI magnitude "
                "remains a labeled parameter — the host-grade column shows "
                "the same model under the FITTED transport rate, bounding "
                "how much the conclusion depends on the assumed number"
            ),
        )


if __name__ == "__main__":
    main()
