"""psanalyze — repo-native static analysis for the PS stack.

The invariants this codebase's correctness rests on — "no thread but the
serve loop touches a native handle", "the PSF2 header is 36 bytes on
both sides of the wire", "the canonical metric keys appear on every
surface" — lived in comments and reviewer memory until PR 12. This
package makes them machine-checked: an AST- and source-level analysis
engine with a pluggable :class:`~tools.psanalyze.core.Rule` framework,
per-line allowlist pragmas (``# psanalyze: ok <rule>``), JSON and human
output, and a nonzero exit on findings so ``make analyze`` gates the
default test path.

Rules shipped (see ``tools/psanalyze/rules/``):

- ``thread-affinity`` — call-graph proof that no non-serve-thread root
  (selectors read loop, metrics-HTTP handlers, profiler thread, data
  pump) reaches a native-handle call site (``wc_*``/``tps_*``/``psq_*``);
- ``cfg-schema`` — the declared job-cfg key registry vs every
  ``cfg[...]``/``cfg.get`` site (typos, dead keys, unsettable keys);
- ``metrics-surface`` — ``PS_SERVER_METRIC_KEYS`` vs the canonical dict
  builder, scrape instruments, ``/health`` rollups, and the
  ``docs/OPERATIONS.md`` tables;
- ``codec-contract`` — flag/method coherence for every ``Codec``
  subclass (aggregate trio, bucketable statelessness, ``nonfinite=``);
- ``abi-drift`` — ``native/*.cpp`` exported signatures, struct layouts,
  magics, and reason enums vs the ctypes bindings and
  ``resilience/frames.py`` constants.

The sixth leg — sanitizer-hardened native builds — is build wiring, not
a static rule: ``make native-asan`` / ``native-ubsan`` / ``native-tsan``
(``tools/native_sanitize.py``).

Run: ``python -m tools.psanalyze [--json] [--root DIR] [--rules a,b]``.
"""

from tools.psanalyze.core import (  # noqa: F401
    AnalysisContext,
    Finding,
    Rule,
    render_human,
    render_json,
    run_analysis,
)
