"""Declared schema of the job ``cfg`` dict — the ground truth the
``cfg-schema`` rule checks every ``cfg[...]`` / ``cfg.get(...)`` site
against.

One entry per key: value type, how the key becomes set, and what it
does. ``settable`` is the contract the rule enforces:

- ``"cli"`` — reachable from its canonical operator CLI (``cli=``,
  default ``examples/train_async.py``; the sharded keys name
  ``examples/train_sharded.py``): the rule fails if THAT file stops
  setting it — a write surviving in some other example does not count;
- ``"caller"`` — a knob for embedding code (benchmarks, smokes, tests,
  other examples) that the async CLI deliberately does not expose;
- ``"internal"`` — set programmatically at runtime (supervisor, fault
  injector), never by an operator.

A key read anywhere in ``pytorch_ps_mpi_tpu/`` or ``examples/`` that is
missing here is a lint failure (the typo case); a key declared here that
nothing reads any more is a lint failure too (the dead-knob case) — the
registry can never drift quietly in either direction.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class CfgKey:
    type: str
    settable: str  # "cli" | "caller" | "internal"
    desc: str
    #: the canonical operator CLI for settable="cli" keys
    cli: str = "examples/train_async.py"


CFG_KEYS = {
    # -- problem / training ------------------------------------------------
    "model": CfgKey("str", "cli", "model registry name (mlp/resnet18/...)"),
    "model_kw": CfgKey("dict", "cli", "model constructor kwargs"),
    "in_shape": CfgKey("list[int]", "cli", "input sample shape"),
    "batch": CfgKey("int", "cli", "per-worker batch size"),
    "seed": CfgKey("int", "cli", "data/init PRNG seed"),
    "optim": CfgKey("str", "cli", "optimizer name (sgd/adam)"),
    "hyper": CfgKey("dict", "cli", "optimizer hyperparameters (lr, ...)"),
    "steps": CfgKey("int", "cli", "gradient pushes per worker"),
    "worker_steps": CfgKey("dict[str,int]", "caller",
                           "per-worker step-count override (keyed by "
                           "worker id string; staleness_bench's ragged "
                           "fleets)"),
    "seq_len": CfgKey("int", "caller",
                      "sequence length for the longcontext/GPT problems"),
    # -- wire / codec ------------------------------------------------------
    "codec": CfgKey("str", "cli", "codec registry name for the PS wire"),
    "codec_kw": CfgKey("dict", "caller", "codec constructor kwargs"),
    "bucket_mb": CfgKey("float", "cli",
                        "flat-bucket wire: ~MB per dtype-grouped bucket "
                        "(0 = per-leaf)"),
    "agg": CfgKey("str", "caller",
                  "homomorphic aggregation: 'auto' (default), 'on' "
                  "(fallbacks counted), 'off' (legacy decode-sum)"),
    "frame_check": CfgKey("bool", "cli",
                          "self-verifying PSF2 frames on every push"),
    "transport": CfgKey("str", "cli", "PS wire: 'shm' or 'tcp'"),
    "max_staleness": CfgKey("int", "cli",
                            "server drops gradients staler than this"),
    # -- timeouts / pacing -------------------------------------------------
    "open_timeout": CfgKey("float", "cli",
                           "worker transport-attach timeout (s)"),
    "push_timeout": CfgKey("float", "cli",
                           "worker push-acknowledge timeout (s); the "
                           "supervisor clamps it for failover detection"),
    "server_timeout": CfgKey("float", "caller",
                             "sharded server-main overall timeout (s)"),
    "tick_interval": CfgKey("float", "caller",
                            "serve-loop tick cadence (s) for health/SLO/"
                            "timeseries sampling"),
    "slow_ms": CfgKey("dict[str,float]", "cli",
                      "injected per-worker straggler delay (ms), keyed "
                      "by worker id string"),
    "server_slow_ms": CfgKey("float", "caller",
                             "injected server-side per-round delay (ms; "
                             "sharded chaos runs)"),
    # -- checkpoint / resilience ------------------------------------------
    "checkpoint_dir": CfgKey("str", "cli",
                             "PS checkpoint directory (sharded path; the "
                             "async CLI passes it to the Supervisor "
                             "directly)",
                             cli="examples/train_sharded.py"),
    "checkpoint_every": CfgKey("int", "cli",
                               "applied-gradient cadence between "
                               "checkpoints",
                               cli="examples/train_sharded.py"),
    "resume": CfgKey("bool", "cli",
                     "restore the latest checkpoint before serving"),
    "resilient": CfgKey("bool", "cli",
                        "workers retry/backoff/reconnect instead of dying"),
    "resilience_kw": CfgKey("dict", "caller",
                            "retry/backoff knob overrides for the "
                            "resilient worker loop"),
    "degraded_round_after": CfgKey("float", "caller",
                                   "sync-barrier: proceed degraded after "
                                   "waiting this long for a dead member"),
    "n_workers": CfgKey("int", "caller",
                        "worker count for sharded server_main (the async "
                        "path passes it as an argument)"),
    # -- fault injection ---------------------------------------------------
    "fault_plan": CfgKey("list[dict]", "cli",
                         "deterministic chaos plan entries "
                         "{at_step, worker, kind}"),
    "fault_seed": CfgKey("int", "cli",
                         "seed for fault randomness (replayable chaos)"),
    "fault_log_dir": CfgKey("str", "cli",
                            "per-process injected-fault JSONL directory"),
    "fault_fired": CfgKey("dict", "internal",
                          "supervisor-maintained map of already-fired "
                          "one-shot faults (survives respawns)"),
    # -- telemetry / observability ----------------------------------------
    "telemetry_dir": CfgKey("str", "cli",
                            "FlightRecorder JSONL + trace/report output "
                            "directory (implies metrics_port=0)"),
    "telemetry_capacity": CfgKey("int", "caller",
                                 "FlightRecorder ring capacity override "
                                 "(events per process)"),
    "metrics_port": CfgKey("int", "cli",
                           "/metrics + /health HTTP port (0 = auto)"),
    "health_port": CfgKey("int", "cli",
                          "arm the HealthMonitor and serve /health on "
                          "this port (0 = auto)"),
    "health": CfgKey("bool", "caller",
                     "arm the HealthMonitor without binding a port "
                     "(sharded / serving-core paths)"),
    "health_dir": CfgKey("str", "cli",
                         "worker beacon-file directory the monitor tails"),
    "health_kw": CfgKey("dict", "caller", "HealthMonitor knob overrides"),
    "numerics": CfgKey("bool", "cli",
                       "arm the NumericsMonitor (NaN quarantine, "
                       "grad-norm stats, fidelity probes)"),
    "numerics_dir": CfgKey("str", "cli",
                           "probe/trajectory JSONL + postmortem directory"),
    "numerics_kw": CfgKey("dict", "cli",
                          "NumericsMonitor knobs (policy, probe_every, "
                          "...)"),
    "lineage": CfgKey("bool", "cli",
                      "arm gradient-lineage tracking (trace IDs on the "
                      "v2 frames)"),
    "lineage_dir": CfgKey("str", "cli",
                          "lineage-server.jsonl output directory"),
    "lineage_kw": CfgKey("dict", "caller", "LineageTracker knob overrides"),
    "anatomy": CfgKey("bool|str", "caller",
                      "round-anatomy causal profiler: 'auto' (default, "
                      "armed whenever lineage is) or False/'off'"),
    "anatomy_kw": CfgKey("dict", "caller",
                         "RoundAnatomy knobs (window, stage_window, "
                         "min_rounds, ...)"),
    "hop_anatomy": CfgKey("bool", "cli",
                          "arm leader-hop occupancy tracing: sub-stage "
                          "timelines + the streaming-headroom board"),
    "hop_anatomy_kw": CfgKey("dict", "caller",
                             "HopAnatomy knobs (window, flush_every, "
                             "ring_capacity, min_rounds, ...)"),
    "timeseries": CfgKey("bool", "cli",
                         "arm the in-process metrics TSDB (/history)"),
    "timeseries_dir": CfgKey("str", "caller",
                             "TSDB persistence directory (falls back to "
                             "telemetry_dir)"),
    "timeseries_kw": CfgKey("dict", "caller", "MetricsHistory knobs"),
    "slo": CfgKey("bool", "cli",
                  "arm the SLO burn-rate watchdog (implies timeseries)"),
    "slo_kw": CfgKey("dict", "cli",
                     "SLO targets/knob overrides ({'targets': {...}})"),
    "freshness": CfgKey("bool", "cli",
                        "arm the read-path freshness tracker "
                        "(publish→edge propagation rows + age plane)"),
    "freshness_kw": CfgKey("dict", "caller",
                           "FreshnessTracker knobs (window, ...)"),
    "profile": CfgKey("bool", "cli",
                      "arm the continuous sampling profiler"),
    "profile_dir": CfgKey("str", "caller",
                          "profiler output directory (falls back to "
                          "telemetry_dir)"),
    "profile_kw": CfgKey("dict", "caller", "SamplingProfiler knobs"),
    "fleet": CfgKey("bool", "caller",
                    "arm the fleet poller without a registration dir"),
    "fleet_dir": CfgKey("str", "cli",
                        "fleet registration directory (/fleet pane)"),
    "fleet_endpoints": CfgKey("list[str]", "caller",
                              "static fleet member endpoints (no "
                              "registration dir)"),
    "fleet_kw": CfgKey("dict", "caller", "FleetMonitor knobs"),
    "fleet_name": CfgKey("str", "caller",
                         "registration name override (default: role name)"),
    "fleet_role": CfgKey("str", "caller",
                         "registration role tag (default 'server')"),
    # -- hierarchical aggregation tree (parallel.tree) ---------------------
    "tree": CfgKey("bool", "caller",
                   "arm the aggregation-tree topology: serve() runs the "
                   "membership-dynamic root barrier with composed-count "
                   "weighted rounds"),
    "group_size": CfgKey("int", "caller",
                         "workers per leaf group (one leader each; the "
                         "last group takes the remainder)"),
    "leader_kw": CfgKey("dict", "caller",
                        "leader-loop knobs (group_transport, group_codec, "
                        "degrade_after, rejoin_every, crash_at_round, "
                        "...) — see parallel.tree.LEADER_KNOBS"),
    "hop_ef": CfgKey("bool", "caller",
                     "per-hop error feedback on the leader's upstream "
                     "re-encode (default True)"),
    "tree_slots": CfgKey("int", "internal",
                         "composed-lineage trailer capacity on pushes to "
                         "the root (max group size; set by run_tree)"),
    "tree_members": CfgKey("list[int]", "internal",
                           "the root barrier's expected pusher ids "
                           "(leader wids; set by run_tree)"),
    "tree_leader": CfgKey("str", "internal",
                          "this leaf worker's group-leader address "
                          "(host:port or shm:<name>; set by run_tree)"),
    "tree_fallback": CfgKey("str", "internal",
                            "the root's address for direct-push fallback "
                            "when the leader dies (set by run_tree)"),
    "tree_async": CfgKey("bool", "caller",
                         "run the tree root WITHOUT the sync barrier "
                         "(each composed frame applies on arrival)"),
    "fleet_meta": CfgKey("dict", "internal",
                         "extra fleet-registration card fields (a tree "
                         "leader's group id + member ids)"),
    # -- self-driving control plane (control.Controller) -------------------
    "control": CfgKey("bool", "cli",
                      "arm the verdict→action controller inside the "
                      "serve loop (codec renegotiation, staleness LR "
                      "weights, evict/readmit, read-tier tuning)"),
    "control_kw": CfgKey("dict", "caller",
                         "Controller knobs (ladder, cooldown_s, "
                         "wire_hi/lo, probation_s, pin, ...) — see "
                         "control.CONTROL_KNOBS"),
    "control_dir": CfgKey("str", "caller",
                          "control-plane directory: action rows "
                          "(control-<name>.jsonl), replay input rows "
                          "(timeseries-control-<name>.jsonl) and the "
                          "worker-polled control-epoch.json (falls "
                          "back to telemetry_dir)"),
    "topo_actions": CfgKey("bool", "caller",
                           "arm STRUCTURAL control actions (the "
                           "controller's topo rule): tree group "
                           "split/merge, elastic read-replica "
                           "scale-out/in, shard split/merge plans — "
                           "knobs (replan_max, replica_min/max, "
                           "shard_split_skew, cooldowns) ride "
                           "control_kw; actions publish through the "
                           "worker-polled control-topo.json"),
    # -- parameter-serving read tier --------------------------------------
    "serving": CfgKey("bool", "caller",
                      "arm the snapshot ring/read tier without binding "
                      "a port"),
    "serving_kw": CfgKey("dict", "cli",
                         "ServingCore knobs (ring, admission_depth, ...)"),
    "read_port": CfgKey("int", "cli",
                        "read-tier listener port (0 = auto)"),
    "read_native": CfgKey("str|bool", "cli",
                          "C++ epoll read tier: 'auto' (default; "
                          "Python-loop fallback), False/'off' to pin "
                          "the Python loop (PS_NO_NATIVE also disarms)",
                          cli="examples/serve_readonly.py"),
    "follow_endpoint": CfgKey("str", "cli",
                              "replica mode: upstream read-tier "
                              "host:port this node subscribes to and "
                              "re-serves (the distribution tree edge)",
                              cli="examples/serve_readonly.py"),
    "follow_fanout": CfgKey("int", "cli",
                            "replica mode: downstream replicas this "
                            "node is provisioned to feed (advertised "
                            "on its fleet card for tree planning)",
                            cli="examples/serve_readonly.py"),
}
