"""Rule 6: telemetry-sidecar prefix registry.

Every JSONL sidecar written under the telemetry directory
(``beacon-*.jsonl``, ``lineage-*.jsonl``, ...) must be declared in
``pytorch_ps_mpi_tpu.telemetry.SIDECAR_PREFIXES``.  The failure mode
this kills: a new observability layer invents ``foo-<name>.jsonl``,
forgets one of the two (previously hand-maintained) exclusion lists,
and its rows silently enter the recorder-span merge — corrupting the
merged Chrome trace and the report's span table on the next live run.
With the registry, that bug class is a lint failure at commit time:

1. every string/f-string literal in the package shaped
   ``<prefix>-...jsonl`` must have its leading dash-terminated prefix
   declared in ``SIDECAR_PREFIXES`` (or be a recorder file —
   ``worker-N.jsonl`` — which is the merge's INPUT, not a sidecar);
2. the registry itself must be well-formed (a dict literal of
   dash-terminated prefixes);
3. both historical copy-sites — ``tools/telemetry_report.py`` dir mode
   and ``examples/train_async.py``'s ``_export_telemetry`` — must
   actually consume the registry, so the consolidation cannot silently
   revert to hand-listing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.psanalyze.core import AnalysisContext, Finding, Rule

TELEMETRY_INIT = "pytorch_ps_mpi_tpu/telemetry/__init__.py"

#: dash-terminated prefixes that are recorder files (the span merge's
#: inputs), not sidecars — the one legitimate undeclared family
RECORDER_PREFIXES: Tuple[str, ...] = ("worker-",)

#: the two sites whose hand-maintained lists the registry replaced;
#: each must reference the registry (by any of its exported names)
CONSUMER_FILES: Tuple[str, ...] = (
    "tools/telemetry_report.py",
    "examples/train_async.py",
)
_REGISTRY_NAMES = ("SIDECAR_PREFIXES", "sidecar_prefix", "is_sidecar")


def _declared_prefixes(ctx: AnalysisContext
                       ) -> Tuple[Optional[Set[str]], int]:
    """Parse the SIDECAR_PREFIXES dict literal's keys out of the
    telemetry package __init__ (no import — the tool must run on a
    broken tree)."""
    tree = ctx.tree(TELEMETRY_INIT)
    if tree is None:
        return None, 1
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "SIDECAR_PREFIXES":
                if not isinstance(value, ast.Dict):
                    return None, node.lineno
                keys = set()
                for k in value.keys:
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        return None, node.lineno
                    keys.add(k.value)
                return keys, node.lineno
    return None, 1


def _jsonl_literal_prefix(node: ast.AST) -> Optional[str]:
    """The leading dash-terminated literal prefix of a ``...jsonl``
    filename literal, or None when the node is not one.

    Handles plain constants (``"faults-server.jsonl"``) and f-strings
    whose LAST piece ends in ``.jsonl`` and whose FIRST piece is a
    literal (``f"beacon-{worker}.jsonl"``).  A name with no dash in its
    leading literal (``server.jsonl``, ``*.jsonl``) has no prefix and
    is not a sidecar pattern.
    """
    lead: Optional[str] = None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if not node.value.endswith(".jsonl"):
            return None
        lead = node.value
    elif isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        if not (isinstance(last, ast.Constant)
                and isinstance(last.value, str)
                and last.value.endswith(".jsonl")):
            return None
        first = node.values[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return None  # fully dynamic name: nothing static to check
        lead = first.value
    if not lead:
        return None
    # the prefix is everything up to and including the FIRST dash of
    # the leading literal ("lineage-leader{g}.jsonl" -> "lineage-")
    dash = lead.find("-")
    if dash < 1:
        return None
    return lead[:dash + 1]


class SidecarRegistryRule(Rule):
    name = "sidecar-registry"
    description = ("every telemetry-dir JSONL sidecar prefix must be "
                   "declared in telemetry.SIDECAR_PREFIXES, and both "
                   "report/export routing sites must consume it")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        declared, line = _declared_prefixes(ctx)
        if declared is None:
            return [Finding(
                self.name, TELEMETRY_INIT, line,
                "SIDECAR_PREFIXES dict literal (str prefix -> report "
                "route) not found in the telemetry package __init__")]
        for p in sorted(declared):
            if not p.endswith("-"):
                findings.append(Finding(
                    self.name, TELEMETRY_INIT, line,
                    f'SIDECAR_PREFIXES key "{p}" must end with "-" '
                    "(prefixes match file names up to the first dash)"))

        # 1) every sidecar-shaped filename literal in the package
        known = declared | set(RECORDER_PREFIXES)
        for rel in ctx.py_files(under=("pytorch_ps_mpi_tpu",)):
            tree = ctx.tree(rel)
            if tree is None:
                continue
            for node in ast.walk(tree):
                pref = _jsonl_literal_prefix(node)
                if pref is None or pref in known:
                    continue
                findings.append(Finding(
                    self.name, rel, node.lineno,
                    f'JSONL sidecar prefix "{pref}" is not declared in '
                    "telemetry.SIDECAR_PREFIXES — its rows would leak "
                    "into the recorder-span merge (declare it with a "
                    "report route, or None for a raw operator log)"))

        # 3) the two historical copy-sites consume the registry
        for rel in CONSUMER_FILES:
            src = ctx.source(rel)
            if src is None:
                # absent surface (the smoke's seeded trees are partial
                # copies): silence, per the engine's degrade convention
                continue
            if not any(name in src for name in _REGISTRY_NAMES):
                findings.append(Finding(
                    self.name, rel, 1,
                    "sidecar routing here no longer consumes "
                    "telemetry.SIDECAR_PREFIXES — the hand-maintained "
                    "exclusion list is back (route through the "
                    "registry instead)"))
        return findings
