"""Rule 4: Codec subclass flag/method coherence.

The codec contract lives in class-level flags whose promises are
checked nowhere at definition time: a codec can claim
``supports_aggregate = True`` and ship without ``agg_decode``, and the
failure surfaces as a serve-loop ``NotImplementedError`` mid-training.
This rule reads every class in ``pytorch_ps_mpi_tpu/codecs/`` and
enforces, statically over the (single-inheritance) class chain:

- ``supports_aggregate`` ⇒ ``aggregate`` + ``agg_decode`` overridden;
- a partial streaming trio (some of ``agg_init``/``agg_fold``/
  ``agg_finalize`` overridden but not all) is incoherent — the base
  default accumulator shape and a partial override cannot compose;
- ``bucketable`` ⇒ stateless: no non-trivial ``init_state`` override
  (per-bucket state has no home — ``codecs/base.py``'s contract);
- ``agg_exact`` set explicitly on a codec that does not claim
  ``supports_aggregate`` is a dead flag (honesty check: the flag only
  means something for an existing algebra);
- ``supports_fused_allreduce`` ⇒ ``fused_allreduce`` +
  ``fused_wire_bits``;
- the hardened lossy four (:data:`HARDENED_NONFINITE`) must accept a
  ``nonfinite=`` constructor kwarg and validate it via
  ``check_nonfinite_mode``.

Flags defined as ``@property`` (ErrorFeedback's delegation) are
dynamic — those classes are skipped for flag checks but still checked
for method-trio coherence.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tools.psanalyze.core import AnalysisContext, Finding, Rule

CODECS_DIR = "pytorch_ps_mpi_tpu/codecs"

#: codecs whose payload statistics a single NaN poisons wholesale — the
#: PR 5 hardening gave them the ``nonfinite=`` guard; dropping it in a
#: refactor would silently reopen the hole
HARDENED_NONFINITE = ("Int8Codec", "QSGDCodec", "SignCodec",
                      "TernGradCodec")

STREAM_TRIO = ("agg_init", "agg_fold", "agg_finalize")


@dataclass
class ClassInfo:
    name: str
    path: str
    line: int
    bases: List[str]
    methods: Set[str] = field(default_factory=set)
    #: flag name -> literal bool value (class-level Assign only)
    flags: Dict[str, bool] = field(default_factory=dict)
    #: flags shadowed by @property (dynamic — skip value checks)
    dynamic_flags: Set[str] = field(default_factory=set)
    #: method name -> its FunctionDef (own defs only)
    defs: Dict[str, ast.FunctionDef] = field(default_factory=dict)


def collect_codec_classes(ctx: AnalysisContext) -> Dict[str, ClassInfo]:
    classes: Dict[str, ClassInfo] = {}
    for rel in ctx.py_files(under=(CODECS_DIR,)):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = ClassInfo(
                name=node.name, path=rel, line=node.lineno,
                bases=[b.id if isinstance(b, ast.Name) else b.attr
                       for b in node.bases
                       if isinstance(b, (ast.Name, ast.Attribute))])
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    deco = {d.id if isinstance(d, ast.Name) else d.attr
                            for d in item.decorator_list
                            if isinstance(d, (ast.Name, ast.Attribute))}
                    if "property" in deco:
                        info.dynamic_flags.add(item.name)
                    else:
                        info.methods.add(item.name)
                        info.defs[item.name] = item
                elif isinstance(item, (ast.Assign, ast.AnnAssign)):
                    targets = (item.targets if isinstance(item, ast.Assign)
                               else [item.target])
                    value = item.value
                    for t in targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if (isinstance(value, ast.Constant)
                                and isinstance(value.value, bool)):
                            info.flags[t.id] = value.value
                        else:
                            # `agg_init = staticmethod(sparse_agg_init)`
                            # style wiring counts as providing the method
                            info.methods.add(t.id)
            classes[node.name] = info
    return classes


def _chain(classes: Dict[str, ClassInfo], name: str) -> List[ClassInfo]:
    """The class and its in-package ancestors (Codec base excluded —
    its generic defaults are what the coherence checks are about)."""
    out: List[ClassInfo] = []
    seen: Set[str] = set()
    todo = [name]
    while todo:
        n = todo.pop(0)
        if n in seen or n == "Codec":
            continue
        seen.add(n)
        info = classes.get(n)
        if info is None:
            continue
        out.append(info)
        todo.extend(info.bases)
    return out


def _is_codec(classes: Dict[str, ClassInfo], name: str) -> bool:
    seen: Set[str] = set()
    todo = [name]
    while todo:
        n = todo.pop(0)
        if n in seen:
            continue
        seen.add(n)
        if n == "Codec":
            return True
        info = classes.get(n)
        if info is not None:
            todo.extend(info.bases)
    return False


class CodecContractRule(Rule):
    name = "codec-contract"
    description = ("Codec subclasses: flags must match the methods they "
                   "promise (aggregate trio, bucketable statelessness, "
                   "nonfinite= hardening)")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        classes = collect_codec_classes(ctx)
        codecs = {n: c for n, c in classes.items()
                  if n != "Codec" and _is_codec(classes, n)}
        for name, info in sorted(codecs.items()):
            chain = _chain(classes, name)
            methods: Set[str] = set()
            flags: Dict[str, bool] = {}
            dynamic: Set[str] = set()
            own_flags: Set[str] = set(info.flags)
            for c in chain:
                methods |= c.methods
                dynamic |= c.dynamic_flags
                for k, v in c.flags.items():
                    flags.setdefault(k, v)  # nearest definition wins

            def flag(k: str) -> Optional[bool]:
                if k in dynamic:
                    return None  # property: dynamic, skip value checks
                return flags.get(k, False)

            if flag("supports_aggregate"):
                for m in ("aggregate", "agg_decode"):
                    if m not in methods:
                        findings.append(Finding(
                            self.name, info.path, info.line,
                            f"{name} claims supports_aggregate but "
                            f"never defines {m}()"))
            claimed = [m for m in STREAM_TRIO if m in methods]
            if claimed and len(claimed) != len(STREAM_TRIO):
                missing = sorted(set(STREAM_TRIO) - set(claimed))
                findings.append(Finding(
                    self.name, info.path, info.line,
                    f"{name} overrides {'/'.join(sorted(claimed))} but "
                    f"not {'/'.join(missing)} — a partial streaming "
                    "trio cannot share an accumulator with the base "
                    "defaults"))
            if flag("bucketable"):
                own_init = next((c.defs.get("init_state") for c in chain
                                 if "init_state" in c.defs), None)
                if own_init is not None and not _returns_empty_tuple(
                        own_init):
                    findings.append(Finding(
                        self.name, info.path, info.line,
                        f"{name} is bucketable but overrides "
                        "init_state() with per-tensor state — bucket "
                        "boundaries cannot carry codec state "
                        "(codecs/base.py contract)"))
            if ("agg_exact" in own_flags
                    and flag("supports_aggregate") is False):
                findings.append(Finding(
                    self.name, info.path, info.line,
                    f"{name} sets agg_exact without "
                    "supports_aggregate — the honesty flag only "
                    "qualifies an existing aggregation algebra"))
            if flag("supports_fused_allreduce"):
                for m in ("fused_allreduce", "fused_wire_bits"):
                    if m not in methods:
                        findings.append(Finding(
                            self.name, info.path, info.line,
                            f"{name} claims supports_fused_allreduce "
                            f"but never defines {m}()"))
            if name in HARDENED_NONFINITE:
                findings.extend(self._check_nonfinite(name, chain))
        return findings

    def _check_nonfinite(self, name: str,
                         chain: List[ClassInfo]) -> List[Finding]:
        init = next((c.defs.get("__init__") for c in chain
                     if "__init__" in c.defs), None)
        info = chain[0]
        if init is None:
            return [Finding(
                self.name, info.path, info.line,
                f"{name} is a hardened lossy codec but has no "
                "__init__ taking the nonfinite= kwarg")]
        args = init.args
        params = {a.arg for a in
                  args.args + args.kwonlyargs + args.posonlyargs}
        if "nonfinite" not in params:
            return [Finding(
                self.name, info.path, init.lineno,
                f"{name}.__init__ lost the nonfinite= kwarg — the "
                "PR 5 NaN-poisoning guard is gone")]
        validated = any(
            isinstance(n, ast.Call) and (
                (isinstance(n.func, ast.Name)
                 and n.func.id == "check_nonfinite_mode")
                or (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "check_nonfinite_mode"))
            for n in ast.walk(init))
        if not validated:
            return [Finding(
                self.name, info.path, init.lineno,
                f"{name}.__init__ takes nonfinite= but never calls "
                "check_nonfinite_mode() — a typo'd mode would surface "
                "mid-training instead of at construction")]
        return []


def _returns_empty_tuple(fn: ast.FunctionDef) -> bool:
    returns = [n for n in ast.walk(fn) if isinstance(n, ast.Return)]
    return bool(returns) and all(
        isinstance(r.value, ast.Tuple) and not r.value.elts
        for r in returns)
