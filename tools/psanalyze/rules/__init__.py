"""Rule registry: importing this module registers every shipped rule."""

from tools.psanalyze.rules.abi_drift import AbiDriftRule
from tools.psanalyze.rules.cfg_schema import CfgSchemaRule
from tools.psanalyze.rules.codec_contract import CodecContractRule
from tools.psanalyze.rules.metrics_surface import MetricsSurfaceRule
from tools.psanalyze.rules.sidecar_registry import SidecarRegistryRule
from tools.psanalyze.rules.thread_affinity import ThreadAffinityRule

ALL_RULES = (
    ThreadAffinityRule,
    CfgSchemaRule,
    MetricsSurfaceRule,
    CodecContractRule,
    AbiDriftRule,
    SidecarRegistryRule,
)
