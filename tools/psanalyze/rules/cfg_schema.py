"""Rule 2: the job-cfg key schema.

Every ``cfg["..."]`` / ``cfg.get("...")`` site in the package and the
examples is checked against the declared registry
(:mod:`tools.psanalyze.cfg_registry`):

- a key read or written that the registry does not declare is a finding
  (the typo case — ``cfg.get("buckt_mb")`` silently returns the default
  forever);
- a registry key declared ``settable="cli"`` that its canonical
  operator CLI (the entry's ``cli=`` file) no longer sets is a finding
  (the operator surface silently shrank — a write surviving in some
  other example does not cover it);
- a registry key nothing reads any more is a finding (the dead-knob
  case — setting it does nothing and nobody notices).

Write scope includes benchmarks/tools (legitimate cfg authors); read
scope is the package + examples, where the job cfg is consumed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tools.psanalyze.cfg_registry import CFG_KEYS
from tools.psanalyze.core import AnalysisContext, Finding, Rule

READ_DIRS = ("pytorch_ps_mpi_tpu", "examples")
WRITE_DIRS = ("pytorch_ps_mpi_tpu", "examples", "benchmarks", "tools")


def _is_cfg(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id == "cfg") or (
        isinstance(node, ast.Attribute) and node.attr == "cfg")


def collect_cfg_sites(
    ctx: AnalysisContext,
) -> Tuple[Dict[str, List[Tuple[str, int]]],
           Dict[str, List[Tuple[str, int]]]]:
    """``(reads, writes)``: cfg key → ``[(path, line), ...]``."""
    reads: Dict[str, List[Tuple[str, int]]] = {}
    writes: Dict[str, List[Tuple[str, int]]] = {}

    def note(d, key, rel, line):
        d.setdefault(key, []).append((rel, line))

    for rel in ctx.py_files(under=WRITE_DIRS):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        in_read_scope = rel.split("/")[0] in READ_DIRS
        for node in ast.walk(tree):
            if (isinstance(node, ast.Subscript) and _is_cfg(node.value)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                key = node.slice.value
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    note(writes, key, rel, node.lineno)
                elif in_read_scope:
                    note(reads, key, rel, node.lineno)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and _is_cfg(node.func.value)
                    and node.func.attr in ("get", "setdefault", "pop")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                key = node.args[0].value
                if in_read_scope:
                    note(reads, key, rel, node.lineno)
                if node.func.attr == "setdefault":
                    note(writes, key, rel, node.lineno)
            elif (isinstance(node, ast.Assign)
                    and any(_is_cfg(t) for t in node.targets)
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        note(writes, k.value, rel, node.lineno)
    return reads, writes


class CfgSchemaRule(Rule):
    name = "cfg-schema"
    description = ("every cfg key site must match the declared registry "
                   "(no typos, no dead knobs, CLI keys stay settable)")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        reads, writes = collect_cfg_sites(ctx)
        # 1) unknown keys (typos) — first site of each
        for key in sorted(set(reads) | set(writes)):
            if key in CFG_KEYS:
                continue
            sites = reads.get(key, []) + writes.get(key, [])
            path, line = sites[0]
            kind = "read" if key in reads else "written"
            findings.append(Finding(
                rule=self.name, path=path, line=line,
                message=(f'cfg key "{key}" {kind} but not declared in '
                         "tools/psanalyze/cfg_registry.py (typo, or a "
                         "new knob missing its registry entry)")))
        # 2) CLI keys must stay settable from THEIR canonical CLI
        for key, info in sorted(CFG_KEYS.items()):
            if info.settable != "cli":
                continue
            if not any(p == info.cli for p, _ in writes.get(key, [])):
                findings.append(Finding(
                    rule=self.name, path=info.cli, line=1,
                    message=(f'cfg key "{key}" is declared settable="cli" '
                             f"but {info.cli} never sets it")))
        # 3) dead knobs: declared but read nowhere
        for key, info in sorted(CFG_KEYS.items()):
            if key not in reads:
                sites = writes.get(key)
                path, line = sites[0] if sites else (
                    "tools/psanalyze/cfg_registry.py", 1)
                findings.append(Finding(
                    rule=self.name, path=path, line=line,
                    message=(f'cfg key "{key}" is declared in the '
                             "registry but nothing reads it any more "
                             "(dead knob — delete the entry or the "
                             "writes)")))
        return findings
