"""Rule 1: thread affinity of native transport handles.

The discipline every PR since 3 re-asserted by hand: the shm/TCP pumps
and every other ctypes entry point are driven from the serve loop (or a
worker main) only — the metrics-HTTP scrape threads, the selectors read
loop, the profiler thread, and the data-prefetch pump touch pure-Python
state exclusively. A native handle crossing onto one of those threads
is a use-after-close or a torn pump away from a crash no test catches
deterministically.

Mechanically: build the package call graph, root it at every discovered
non-serve-thread entry point (``threading.Thread`` targets, HTTP
``do_*`` handlers, the callables registered on ``MetricsHTTPServer``),
and flag any root from which a ``wc_*``/``tps_*``/``psq_*`` call site is
reachable. Sanctioned exceptions — the atomic-counter profile-stats
reads that never hold a handle — carry ``# psanalyze: ok
thread-affinity`` pragmas at the call site.
"""

from __future__ import annotations

from typing import List

from tools.psanalyze.callgraph import build_callgraph
from tools.psanalyze.core import AnalysisContext, Finding, Rule

#: def names (EXACT match on the function's own name) of thread targets
#: that ARE the serve loop — the native handles' home thread — and so
#: are sanctioned roots, not violations. A renamed/wrapped serve entry
#: that trips the rule takes a `# psanalyze: ok thread-affinity` pragma
#: at the call site (or a new entry here) — the explicit audit trail is
#: the point.
SERVE_THREAD_NAMES = (
    "serve", "worker_main", "server_main", "_serve_loop", "run_steps",
)


class ThreadAffinityRule(Rule):
    name = "thread-affinity"
    description = (
        "no non-serve-thread root (HTTP handlers, selectors loop, "
        "profiler, data pump) may reach a native wc_*/tps_*/psq_* "
        "call site")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = build_callgraph(ctx)
        findings: List[Finding] = []
        seen = set()
        for root in graph.roots:
            simple = root.qname.split("::")[-1].rsplit(".", 1)[-1]
            if simple in SERVE_THREAD_NAMES:
                continue
            hit = graph.reachable_native(root.qname)
            if hit is None:
                continue
            chain, (symbol, line) = hit
            site = graph.defs[chain[-1]]
            key = (site.path, line, symbol, root.qname)
            if key in seen:
                continue
            seen.add(key)
            pretty = " -> ".join(q.split("::")[-1] for q in chain)
            findings.append(Finding(
                rule=self.name, path=site.path, line=line,
                message=(
                    f"native call {symbol}() reachable from "
                    f"{root.reason} root {root.qname.split('::')[-1]} "
                    f"({root.path}:{root.line}) via {pretty}"),
            ))
        return findings
