"""Rule 3: metrics-surface consistency.

``PS_SERVER_METRIC_KEYS`` is the canonical schema every PS server emits.
A key added to one surface but not the others used to be doc rot; this
rule makes it a lint failure. Checked surfaces:

1. the canonical tuple vs the one dict builder
   (``ps_server_metrics``'s return literal) — exact set equality;
2. the scrape registry: every canonical key maps (via
   :data:`INSTRUMENT_MAP`) to a ``ps_*`` instrument name that must
   appear in package source, and the map itself must cover exactly the
   canonical keys — adding a canonical key forces a conscious decision
   about its scrape twin;
3. the ``/health`` builders: the fleet rollup subset
   (``HEALTH_FLEET_ROLLUP_KEYS``) must be importable from the registry
   module and a subset of the canonical keys, and every ``m["..."]``
   subscript on a ``ps_server_metrics(...)`` result must name a
   canonical key;
4. ``docs/OPERATIONS.md``: every canonical key appears (backticked)
   somewhere in the operations doc;
5. no transport forks the schema: a class mixing in
   ``PSServerTelemetry`` must not define its own ``metrics``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.psanalyze.core import (
    AnalysisContext,
    Finding,
    Rule,
    str_tuple,
)

REGISTRY_PY = "pytorch_ps_mpi_tpu/telemetry/registry.py"
OPERATIONS_MD = "docs/OPERATIONS.md"

#: canonical metrics() key -> scrape instrument name (None = deliberately
#: not instrumented; the canonical dict / TSDB is its only scrape form)
INSTRUMENT_MAP: Dict[str, Optional[str]] = {
    "ts": "ps_scrape_ts_seconds",
    "uptime_s": "ps_uptime_seconds",
    "grads_received": "ps_grads_received_total",
    "bytes_received": "ps_wire_bytes_received_total",
    "raw_bytes_per_grad": "ps_raw_bytes_per_grad",
    "wire_bytes_per_grad": "ps_wire_bytes_per_grad",
    "compression_ratio": "ps_compression_ratio",
    "stale_drops": "ps_stale_drops_total",
    "bucket_count": "ps_bucket_count",
    "wire_units_per_push": "ps_wire_units_per_push",
    "frames_rejected": "ps_frames_rejected_total",
    "staleness_p50": "ps_staleness_p50",
    "staleness_p95": "ps_staleness_p95",
    "staleness_p99": "ps_staleness_p99",
    "nonfinite_total": "ps_nonfinite_total",
    "grad_norm": "ps_grad_norm",
    "update_ratio": "ps_update_ratio",
    "codec_rel_error": "ps_codec_rel_error",
    "ef_residual_norm": "ps_ef_residual_norm",
    "agg_mode": "ps_agg_mode",
    "decodes_per_publish": "ps_decodes_per_publish",
    "agg_fallbacks": "ps_agg_fallbacks_total",
    "tree_composed": "ps_tree_composed_total",
    "lineage_pushes": "ps_lineage_pushes_total",
    "push_e2e_p50_ms": "ps_push_e2e_p50_ms",
    "push_e2e_p95_ms": "ps_push_e2e_p95_ms",
    "anatomy_rounds": "ps_anatomy_rounds_total",
    "anatomy_wire_share": "ps_anatomy_wire_share",
    "anatomy_top_saving_frac": "ps_anatomy_top_saving_frac",
    "reads_total": "ps_reads_total",
    "read_p50_ms": "ps_read_p50_ms",
    "read_p95_ms": "ps_read_p95_ms",
    "delta_bytes_saved": "ps_delta_bytes_saved_total",
    "reads_shed": "ps_reads_shed_total",
    "coalesce_hits": "ps_coalesce_hits_total",
    "reads_not_modified": "ps_reads_not_modified_total",
    "native_read_conns": "ps_native_read_conns",
    "replica_lag_versions": "ps_replica_lag_versions",
    "follower_bytes_relayed": "ps_follower_bytes_relayed_total",
    "control_actions": "ps_control_actions_total",
    "control_epoch": "ps_control_epoch",
    "control_evicted": "ps_control_evicted",
    "control_lr_scale_min": "ps_control_lr_scale_min",
    "topo_actions": "ps_topo_actions_total",
    "replicas_live": "ps_replicas_live",
    "group_replans": "ps_group_replans_total",
    "read_fresh_p50_ms": "ps_read_fresh_p50_ms",
    "read_fresh_p95_ms": "ps_read_fresh_p95_ms",
    "serving_age_ms": "ps_serving_age_ms",
    "fresh_hop_count": "ps_fresh_hop_count",
    "hop_rounds": "ps_hop_rounds_total",
    "hop_busy_frac": "ps_hop_busy_frac",
    "hop_ingest_wait_ms": "ps_hop_ingest_wait_ms",
    "hop_stream_headroom_ratio": "ps_hop_stream_headroom_ratio",
    "hop_serial_ms": "ps_hop_serial_ms",
    "hop_ring_drops": "ps_hop_ring_drops_total",
}


def _find_assign_tuple(tree: ast.Module, name: str
                       ) -> Tuple[Optional[Tuple[str, ...]], int]:
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                return str_tuple(value), node.lineno
    return None, 1


def _return_dict_keys(fn: ast.FunctionDef) -> Set[str]:
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
    return keys


def _ps_string_literals(ctx: AnalysisContext) -> Set[str]:
    """Every ``ps_*`` string constant in the package — the existence
    ground for instrument names (robust to names built in loops)."""
    out: Set[str] = set()
    pat = re.compile(r"^ps_[a-z0-9_]+$")
    for rel in ctx.py_files(under=("pytorch_ps_mpi_tpu",)):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and pat.match(node.value)):
                out.add(node.value)
    return out


class MetricsSurfaceRule(Rule):
    name = "metrics-surface"
    description = ("PS_SERVER_METRIC_KEYS, the metrics() builder, scrape "
                   "instruments, /health rollups and docs/OPERATIONS.md "
                   "must agree key-for-key")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        tree = ctx.tree(REGISTRY_PY)
        if tree is None:
            return [Finding(self.name, REGISTRY_PY, 1,
                            "cannot parse the canonical metrics module")]
        canon, canon_line = _find_assign_tuple(tree, "PS_SERVER_METRIC_KEYS")
        if canon is None:
            return [Finding(self.name, REGISTRY_PY, 1,
                            "PS_SERVER_METRIC_KEYS tuple literal not found")]
        canon_set = set(canon)

        # 1) the one dict builder
        builder = next((n for n in ast.walk(tree)
                        if isinstance(n, ast.FunctionDef)
                        and n.name == "ps_server_metrics"), None)
        if builder is None:
            findings.append(Finding(
                self.name, REGISTRY_PY, 1,
                "ps_server_metrics() not found beside "
                "PS_SERVER_METRIC_KEYS"))
        else:
            built = _return_dict_keys(builder)
            for k in sorted(canon_set - built):
                findings.append(Finding(
                    self.name, REGISTRY_PY, builder.lineno,
                    f'canonical key "{k}" missing from the '
                    "ps_server_metrics() return dict"))
            for k in sorted(built - canon_set):
                findings.append(Finding(
                    self.name, REGISTRY_PY, builder.lineno,
                    f'ps_server_metrics() emits "{k}" which is not in '
                    "PS_SERVER_METRIC_KEYS"))

        # 2) scrape instruments via the declared map
        for k in sorted(canon_set - set(INSTRUMENT_MAP)):
            findings.append(Finding(
                self.name, REGISTRY_PY, canon_line,
                f'canonical key "{k}" has no INSTRUMENT_MAP entry '
                "(tools/psanalyze/rules/metrics_surface.py) — declare "
                "its scrape instrument, or map it to None deliberately"))
        for k in sorted(set(INSTRUMENT_MAP) - canon_set):
            findings.append(Finding(
                self.name, REGISTRY_PY, canon_line,
                f'INSTRUMENT_MAP names "{k}" which is no longer a '
                "canonical key"))
        literals = _ps_string_literals(ctx)
        for k, inst in sorted(INSTRUMENT_MAP.items()):
            if k in canon_set and inst is not None and inst not in literals:
                findings.append(Finding(
                    self.name, REGISTRY_PY, canon_line,
                    f'scrape instrument "{inst}" (canonical key "{k}") '
                    "not emitted anywhere in the package"))

        # 3) /health builders
        rollup, rollup_line = _find_assign_tuple(
            tree, "HEALTH_FLEET_ROLLUP_KEYS")
        if rollup is None:
            findings.append(Finding(
                self.name, REGISTRY_PY, 1,
                "HEALTH_FLEET_ROLLUP_KEYS not found in the registry "
                "module (the /health fleet rollup must import its key "
                "subset from the canonical schema's home)"))
        else:
            for k in sorted(set(rollup) - canon_set):
                findings.append(Finding(
                    self.name, REGISTRY_PY, rollup_line,
                    f'HEALTH_FLEET_ROLLUP_KEYS names "{k}" which is not '
                    "a canonical key"))
        findings.extend(self._check_metric_subscripts(ctx, canon_set))

        # 4) the operations doc
        md = ctx.source(OPERATIONS_MD)
        if md is None:
            findings.append(Finding(
                self.name, OPERATIONS_MD, 1,
                "docs/OPERATIONS.md missing — the canonical metric keys "
                "must stay documented"))
        else:
            # keys count only INSIDE code context: a fenced ``` block,
            # or a single-line inline `span` (fences are pulled out
            # FIRST — their odd backtick counts desync naive pairing —
            # and inline spans pair per line, so a raw `...key...`
            # regex can never bridge two adjacent spans and accept
            # un-ticked prose). Match is word-bounded within the span
            # ("`staleness_p50/p95/p99`", "`reads_total` +").
            spans = re.findall(r"```.*?```", md, re.S)
            fenceless = re.sub(r"```.*?```", "", md, flags=re.S)
            for line in fenceless.splitlines():
                spans.extend(re.findall(r"`([^`]+)`", line))
            for k in sorted(canon_set):
                pat = re.compile(r"\b%s\b" % re.escape(k))
                if any(pat.search(s) for s in spans):
                    continue
                findings.append(Finding(
                    self.name, OPERATIONS_MD, 1,
                    f'canonical metric key "{k}" is not documented in '
                    "docs/OPERATIONS.md"))

        # 5) no transport forks metrics()
        for rel in ctx.py_files(under=("pytorch_ps_mpi_tpu",)):
            t = ctx.tree(rel)
            if t is None or rel == REGISTRY_PY:
                continue
            for node in ast.walk(t):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = {b.id if isinstance(b, ast.Name) else b.attr
                         for b in node.bases
                         if isinstance(b, (ast.Name, ast.Attribute))}
                if "PSServerTelemetry" not in bases:
                    continue
                for item in node.body:
                    if (isinstance(item, ast.FunctionDef)
                            and item.name == "metrics"):
                        findings.append(Finding(
                            self.name, rel, item.lineno,
                            f"{node.name} overrides metrics() — the "
                            "canonical schema must not fork per "
                            "transport (extend ps_server_metrics "
                            "instead)"))
        return findings

    def _check_metric_subscripts(self, ctx: AnalysisContext,
                                 canon: Set[str]) -> List[Finding]:
        """In the telemetry package: every string subscript on a name
        bound from ``ps_server_metrics(...)`` / ``.metrics()`` must be a
        canonical key."""
        findings: List[Finding] = []
        for rel in ctx.py_files(under=("pytorch_ps_mpi_tpu",)):
            tree = ctx.tree(rel)
            if tree is None:
                continue
            for fn in ast.walk(tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                bound: Set[str] = set()
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)):
                        f = node.value.func
                        callee = (f.id if isinstance(f, ast.Name)
                                  else f.attr if isinstance(f, ast.Attribute)
                                  else None)
                        if callee in ("ps_server_metrics", "metrics"):
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    bound.add(t.id)
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Subscript)
                            and isinstance(node.value, ast.Name)
                            and node.value.id in bound
                            and isinstance(node.slice, ast.Constant)
                            and isinstance(node.slice.value, str)
                            and node.slice.value not in canon):
                        findings.append(Finding(
                            self.name, rel, node.lineno,
                            f'"{node.slice.value}" read from a canonical '
                            "metrics dict but not in "
                            "PS_SERVER_METRIC_KEYS"))
        return findings
