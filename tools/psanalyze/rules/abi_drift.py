"""Rule 5: C++/Python ABI drift.

The three native libraries are reached through hand-maintained ctypes
tables, and the PSF2 wire header plus the batched-ingest structs are
defined twice — once in C++, once in Python. Nothing at runtime checks
that the two sides still agree (a same-byte-count layout mismatch is
the documented-undetectable failure class from the PR 2 postmortem), so
this rule diffs them at analysis time:

- every ``lib.X.argtypes``/``restype`` binding (and every bare
  ``lib.X(...)`` call) in ``parallel/tcp.py``, ``parallel/dcn.py`` and
  ``utils/native.py`` against the exported signature parsed from
  ``native/*.cpp`` — arity, per-argument type, return width;
- ``resilience/frames.py``'s header constants (``FRAME_MAGIC``/``_V1``,
  the ``_HEADER`` struct format, the ``HEADER_BYTES == 36`` assert)
  against ``tcpps.cpp``'s ``kPsfMagicV2``/``V1``/``kPsfHeader`` and the
  ``PsfHeader`` field list;
- the ``FrameStatus`` reason enum against ``frames.BATCH_REASONS``;
- the ``BatchMeta`` struct (fields, packed size, the 48-byte asserts)
  against ``tcp.py``'s ``_BatchMeta`` mirror.

The runtime twin: ``tcp.py`` re-asserts header size / magic / reason
names through the ``tps_abi_*`` exports at library load.
"""

from __future__ import annotations

import ast
import re
import struct as pystruct
from typing import Dict, List, Optional, Tuple

from tools.psanalyze.core import AnalysisContext, Finding, Rule

BINDING_MODULES = {
    "pytorch_ps_mpi_tpu/parallel/tcp.py": "native/tcpps.cpp",
    "pytorch_ps_mpi_tpu/parallel/dcn.py": "native/psqueue.cpp",
    "pytorch_ps_mpi_tpu/utils/native.py": "native/wirecodec.cpp",
    # read-plane entry points (tps_read_* / tps_abi_psr_*) live in the
    # same library as the TPS1 wire but bind from the serving package
    "pytorch_ps_mpi_tpu/serving/native_read.py": "native/tcpps.cpp",
}
FRAMES_PY = "pytorch_ps_mpi_tpu/resilience/frames.py"
TCPPS_CPP = "native/tcpps.cpp"
TCP_PY = "pytorch_ps_mpi_tpu/parallel/tcp.py"
NET_PY = "pytorch_ps_mpi_tpu/serving/net.py"
NATIVE_READ_PY = "pytorch_ps_mpi_tpu/serving/native_read.py"
WIRECODEC_CPP = "native/wirecodec.cpp"
NATIVE_PY = "pytorch_ps_mpi_tpu/utils/native.py"

_NATIVE_RE = re.compile(r"\b(?:wc|tps|psq)_[A-Za-z0-9_]+")

# -- C side -----------------------------------------------------------------

_C_SCALARS = {
    "void": "void", "int": "int", "float": "f32", "double": "f64",
    "size_t": "usize", "int8_t": "i8", "uint8_t": "u8",
    "int32_t": "i32", "uint16_t": "u16", "uint32_t": "u32",
    "uint64_t": "u64", "int64_t": "i64", "char": "char",
}


def c_type_norm(raw: str) -> str:
    s = raw.replace("const", " ").strip()
    ptr = s.count("*")
    s = s.replace("*", " ").split()
    base = s[0] if s else ""
    tok = _C_SCALARS.get(base, base)
    if ptr:
        if tok == "void":
            return "ptr"
        if tok == "char":
            return "cstr"
        return tok + "p" * ptr
    return tok


_C_FUNC = re.compile(
    r"^[ \t]*((?:const[ \t]+)?[A-Za-z_][A-Za-z0-9_]*[ \t*]*?)[ \t]+"
    r"((?:wc|tps|psq)_[A-Za-z0-9_]*)[ \t]*\(", re.M)


def parse_c_exports(src: str) -> Dict[str, Tuple[str, List[str], int]]:
    """name -> (return token, [arg tokens], line)."""
    out: Dict[str, Tuple[str, List[str], int]] = {}
    for m in _C_FUNC.finditer(src):
        ret = c_type_norm(m.group(1))
        name = m.group(2)
        i = m.end()
        depth = 1
        while i < len(src) and depth:
            if src[i] == "(":
                depth += 1
            elif src[i] == ")":
                depth -= 1
            i += 1
        params = src[m.end():i - 1].strip()
        args: List[str] = []
        if params and params != "void":
            for p in params.split(","):
                p = p.strip()
                # drop the parameter name (last identifier not part of
                # the type) unless the param is a bare type
                pm = re.match(r"(.*?)([A-Za-z_][A-Za-z0-9_]*)?$", p)
                args.append(c_type_norm(pm.group(1) or p))
        line = src[:m.start()].count("\n") + 1
        out[name] = (ret, args, line)
    return out


def parse_c_const(src: str, name: str) -> Optional[int]:
    m = re.search(
        r"\b%s\s*=\s*(0[xX][0-9a-fA-F]+|\d+)" % re.escape(name), src)
    return int(m.group(1), 0) if m else None


def parse_c_struct(src: str, name: str) -> Optional[List[Tuple[str, str]]]:
    m = re.search(r"struct\s+%s\s*\{(.*?)\};" % re.escape(name), src,
                  re.S)
    if m is None:
        return None
    fields: List[Tuple[str, str]] = []
    for line in m.group(1).splitlines():
        line = line.split("//")[0].strip()
        fm = re.match(r"([A-Za-z_][A-Za-z0-9_ ]*\**)\s+"
                      r"([A-Za-z_][A-Za-z0-9_]*)\s*;", line)
        if fm:
            fields.append((fm.group(2), c_type_norm(fm.group(1))))
    return fields


def parse_c_enum(src: str, name: str) -> Optional[Dict[int, str]]:
    m = re.search(r"enum\s+%s[^{]*\{(.*?)\};" % re.escape(name), src, re.S)
    if m is None:
        return None
    out: Dict[int, str] = {}
    for em in re.finditer(r"([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(\d+)",
                          m.group(1)):
        out[int(em.group(2))] = em.group(1)
    return out


# -- Python side ------------------------------------------------------------

_PY_CTYPES = {
    "c_void_p": "ptr", "c_char_p": "cstr", "c_int": "int",
    "c_float": "f32", "c_double": "f64", "c_size_t": "usize",
    "c_int8": "i8", "c_uint8": "u8", "c_int32": "i32",
    "c_uint16": "u16", "c_uint32": "u32", "c_uint64": "u64",
    "c_int64": "i64", "c_bool": "bool",
}

_SIZES = {"u8": 1, "i8": 1, "u16": 2, "i16": 2, "u32": 4, "i32": 4,
          "u64": 8, "i64": 8, "f32": 4, "f64": 8, "int": 4}

_FMT_CHARS = {"B": "u8", "b": "i8", "H": "u16", "h": "i16", "I": "u32",
              "i": "i32", "Q": "u64", "q": "i64", "f": "f32", "d": "f64"}


def _py_type_token(node: ast.AST, aliases: Dict[str, str]) -> str:
    if isinstance(node, ast.Name):
        return aliases.get(node.id, _PY_CTYPES.get(node.id, node.id))
    if isinstance(node, ast.Attribute):  # ctypes.c_x
        return _PY_CTYPES.get(node.attr, node.attr)
    if isinstance(node, ast.Call):
        fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                 else node.func.id if isinstance(node.func, ast.Name)
                 else "")
        if fname == "POINTER" and node.args:
            inner = _py_type_token(node.args[0], aliases)
            # the ctypes mirror class is _BatchMeta; the C struct is
            # BatchMeta — same type, normalize to one token
            return inner.lstrip("_") + "p"
    return "?"


def parse_py_bindings(tree: ast.Module
                      ) -> Dict[str, Dict[str, object]]:
    """name -> {"argtypes": [tokens], "restype": token, "line": int}
    from ``lib.X.argtypes = [...]`` / ``lib.X.restype = T`` assigns."""
    aliases: Dict[str, str] = {}
    out: Dict[str, Dict[str, object]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if isinstance(t, ast.Name):
            tok = _py_type_token(node.value, aliases)
            if tok.endswith("p") and tok != "?":
                aliases[t.id] = tok
            continue
        if not (isinstance(t, ast.Attribute)
                and t.attr in ("argtypes", "restype")
                and isinstance(t.value, ast.Attribute)):
            continue
        fn = t.value.attr
        if not _NATIVE_RE.fullmatch(fn):
            continue
        entry = out.setdefault(fn, {"line": node.lineno})
        if t.attr == "argtypes":
            elts = (node.value.elts
                    if isinstance(node.value, (ast.List, ast.Tuple))
                    else [])
            entry["argtypes"] = [_py_type_token(e, aliases) for e in elts]
        else:
            entry["restype"] = _py_type_token(node.value, aliases)
    return out


def parse_py_calls(tree: ast.Module) -> Dict[str, int]:
    """name -> first line of an attribute call on a native symbol
    (AST-walked, so symbol mentions in comments/docstrings — which this
    codebase's prose is full of — never count as calls)."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _NATIVE_RE.fullmatch(node.func.attr)):
            out.setdefault(node.func.attr, node.lineno)
    return out


def _module_const(tree: ast.Module, name: str) -> Optional[int]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id == name
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, int)):
                    return node.value.value
    return None


def _struct_fmt(tree: ast.Module, name: str) -> Optional[str]:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)
                and isinstance(node.value, ast.Call)
                and node.value.args
                and isinstance(node.value.args[0], ast.Constant)
                and isinstance(node.value.args[0].value, str)):
            return node.value.args[0].value
    return None


def _dict_literal(tree: ast.Module, name: str
                  ) -> Optional[Dict[int, str]]:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            out: Dict[int, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, int)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out[k.value] = v.value
            return out
    return None


def _ctypes_fields(tree: ast.Module, cls_name: str
                   ) -> Optional[List[Tuple[str, str]]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for item in node.body:
                if (isinstance(item, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "_fields_"
                                for t in item.targets)
                        and isinstance(item.value, ast.List)):
                    fields = []
                    for el in item.value.elts:
                        if (isinstance(el, ast.Tuple)
                                and len(el.elts) == 2
                                and isinstance(el.elts[0], ast.Constant)):
                            fields.append((
                                el.elts[0].value,
                                _py_type_token(el.elts[1], {})))
                    return fields
    return None


class AbiDriftRule(Rule):
    name = "abi-drift"
    description = ("native/*.cpp exported signatures, header constants, "
                   "structs and reason enums must match the ctypes "
                   "bindings and frames.py")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_bindings(ctx))
        findings.extend(self._check_frame_constants(ctx))
        findings.extend(self._check_batch_meta(ctx))
        findings.extend(self._check_read_stats(ctx))
        findings.extend(self._check_hop_rings(ctx))
        findings.extend(self._check_reason_enum(ctx))
        return findings

    # -- function signatures ----------------------------------------------
    def _check_bindings(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for py_rel, cpp_rel in BINDING_MODULES.items():
            tree = ctx.tree(py_rel)
            cpp = ctx.source(cpp_rel)
            if tree is None or cpp is None:
                continue
            exports = parse_c_exports(cpp)
            bindings = parse_py_bindings(tree)
            calls = parse_py_calls(tree)
            for fn in sorted(set(bindings) | set(calls)):
                line = int(bindings.get(fn, {}).get(
                    "line", calls.get(fn, 1)))
                if fn not in exports:
                    findings.append(Finding(
                        self.name, py_rel, line,
                        f"{fn} bound/called from Python but not "
                        f"exported by {cpp_rel}"))
                    continue
                ret, cargs, _cline = exports[fn]
                b = bindings.get(fn)
                if b is None:
                    # bare call without declared types: only safe for
                    # void/int returns and pointer-free out-params
                    if ret not in ("void", "int", "u32", "u16"):
                        findings.append(Finding(
                            self.name, py_rel, line,
                            f"{fn} returns {ret} in C but is called "
                            "without a declared restype (ctypes "
                            "defaults to int — truncation)"))
                    continue
                pargs = b.get("argtypes")
                if pargs is not None:
                    if len(pargs) != len(cargs):
                        findings.append(Finding(
                            self.name, py_rel, line,
                            f"{fn}: argtypes declares {len(pargs)} "
                            f"argument(s), {cpp_rel} exports "
                            f"{len(cargs)}"))
                    else:
                        for i, (p, c) in enumerate(zip(pargs, cargs)):
                            if p != c:
                                findings.append(Finding(
                                    self.name, py_rel, line,
                                    f"{fn}: argument {i} is {p} in "
                                    f"ctypes but {c} in {cpp_rel}"))
                restype = b.get("restype")
                if restype is not None and restype != ret:
                    findings.append(Finding(
                        self.name, py_rel, line,
                        f"{fn}: restype is {restype} in ctypes but "
                        f"the C export returns {ret}"))
                if restype is None and ret not in ("void", "int"):
                    findings.append(Finding(
                        self.name, py_rel, line,
                        f"{fn}: C returns {ret} but no restype is "
                        "declared (ctypes defaults to int)"))
        return findings

    # -- PSF2 header constants --------------------------------------------
    def _check_frame_constants(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        tree = ctx.tree(FRAMES_PY)
        cpp = ctx.source(TCPPS_CPP)
        if tree is None or cpp is None:
            return findings
        fmt = _struct_fmt(tree, "_HEADER")
        k_hdr = parse_c_const(cpp, "kPsfHeader")
        if fmt is not None and k_hdr is not None:
            py_size = pystruct.calcsize(fmt)
            if py_size != k_hdr:
                findings.append(Finding(
                    self.name, FRAMES_PY, 1,
                    f"PSF2 header is {py_size} bytes in frames.py "
                    f"(_HEADER {fmt!r}) but kPsfHeader is {k_hdr} in "
                    f"{TCPPS_CPP}"))
            c_fields = parse_c_struct(cpp, "PsfHeader")
            if c_fields is not None:
                py_seq = [_FMT_CHARS.get(ch, "?") for ch in fmt
                          if ch in _FMT_CHARS]
                c_seq = [t for _n, t in c_fields]
                if py_seq != c_seq:
                    findings.append(Finding(
                        self.name, FRAMES_PY, 1,
                        f"PSF2 header field layout drifted: frames.py "
                        f"packs {py_seq} but PsfHeader holds {c_seq}"))
        for py_name, c_name in (("FRAME_MAGIC", "kPsfMagicV2"),
                                ("FRAME_MAGIC_V1", "kPsfMagicV1")):
            py_v = _module_const(tree, py_name)
            c_v = parse_c_const(cpp, c_name)
            if py_v is not None and c_v is not None and py_v != c_v:
                findings.append(Finding(
                    self.name, FRAMES_PY, 1,
                    f"{py_name} is 0x{py_v:08x} in frames.py but "
                    f"{c_name} is 0x{c_v:08x} in {TCPPS_CPP}"))
        return findings

    # -- BatchMeta struct --------------------------------------------------
    def _check_batch_meta(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        tree = ctx.tree(TCP_PY)
        cpp = ctx.source(TCPPS_CPP)
        if tree is None or cpp is None:
            return findings
        c_fields = parse_c_struct(cpp, "BatchMeta")
        py_fields = _ctypes_fields(tree, "_BatchMeta")
        if c_fields is None or py_fields is None:
            findings.append(Finding(
                self.name, TCP_PY, 1,
                "BatchMeta (C) or _BatchMeta (ctypes) struct not found "
                "— the batched-ingest meta mirror is gone"))
            return findings
        if [(n, t) for n, t in c_fields] != [(n, t) for n, t in py_fields]:
            findings.append(Finding(
                self.name, TCP_PY, 1,
                f"BatchMeta layout drifted: C has {c_fields}, ctypes "
                f"mirror has {py_fields}"))
        size = sum(_SIZES.get(t, 0) for _n, t in c_fields)
        m = re.search(r"sizeof\(BatchMeta\)\s*==\s*(\d+)", cpp)
        asserted = int(m.group(1)) if m else None
        if asserted is not None and size != asserted:
            findings.append(Finding(
                self.name, TCP_PY, 1,
                f"BatchMeta packs to {size} bytes but {TCPPS_CPP} "
                f"asserts {asserted}"))
        return findings

    # -- ReadStats struct + PSR1 magic (read plane) ------------------------
    def _check_read_stats(self, ctx: AnalysisContext) -> List[Finding]:
        """The read-plane twin of :meth:`_check_batch_meta`: the native
        ``ReadStats`` counter block is mirrored field-for-field by
        ``native_read.py``'s ``_ReadStats`` ctypes struct, and the PSR1
        wire magic is defined once per side (``serving/net.py`` vs
        ``kPsrMagic``). The runtime twin re-checks magic and struct
        sizes through the ``tps_abi_*`` exports at library load."""
        findings: List[Finding] = []
        tree = ctx.tree(NATIVE_READ_PY)
        cpp = ctx.source(TCPPS_CPP)
        if tree is None or cpp is None:
            return findings
        # both read-plane mirrors: the counter block and the per-tenant
        # freshness export ride the same static_assert/ctypes discipline
        for c_name, py_name in (("ReadStats", "_ReadStats"),
                                ("ReadFreshStats", "_ReadFreshStats")):
            c_fields = parse_c_struct(cpp, c_name)
            py_fields = _ctypes_fields(tree, py_name)
            if c_fields is None or py_fields is None:
                findings.append(Finding(
                    self.name, NATIVE_READ_PY, 1,
                    f"{c_name} (C) or {py_name} (ctypes) struct not "
                    "found — the read-plane stats mirror is gone"))
                continue
            if [(n, t) for n, t in c_fields] != \
                    [(n, t) for n, t in py_fields]:
                findings.append(Finding(
                    self.name, NATIVE_READ_PY, 1,
                    f"{c_name} layout drifted: C has {c_fields}, ctypes "
                    f"mirror has {py_fields}"))
            size = sum(_SIZES.get(t, 0) for _n, t in c_fields)
            m = re.search(r"sizeof\(%s\)\s*==\s*(\d+)" % c_name, cpp)
            asserted = int(m.group(1)) if m else None
            if asserted is not None and size != asserted:
                findings.append(Finding(
                    self.name, NATIVE_READ_PY, 1,
                    f"{c_name} packs to {size} bytes but {TCPPS_CPP} "
                    f"asserts {asserted}"))
        net_tree = ctx.tree(NET_PY)
        if net_tree is not None:
            py_magic = _module_const(net_tree, "MAGIC")
            c_magic = parse_c_const(cpp, "kPsrMagic")
            if (py_magic is not None and c_magic is not None
                    and py_magic != c_magic):
                findings.append(Finding(
                    self.name, NET_PY, 1,
                    f"PSR1 magic is 0x{py_magic:08x} in net.py but "
                    f"kPsrMagic is 0x{c_magic:08x} in {TCPPS_CPP}"))
        return findings

    # -- hop-anatomy interval rings ----------------------------------------
    def _check_hop_rings(self, ctx: AnalysisContext) -> List[Finding]:
        """The occupancy plane's twin pair: the per-frame validate
        stamp (``HopStamp``, tcpps) and the per-fold-call span
        (``FoldSpan``, wirecodec) ride bounded native rings drained
        into ctypes mirrors — same static_assert/ctypes discipline as
        ``BatchMeta``/``ReadStats``, plus the runtime ``*_abi_*_bytes``
        size re-check at library load."""
        findings: List[Finding] = []
        for c_name, py_name, py_path, cpp_path in (
                ("HopStamp", "_HopStamp", TCP_PY, TCPPS_CPP),
                ("FoldSpan", "_FoldSpan", NATIVE_PY, WIRECODEC_CPP)):
            tree = ctx.tree(py_path)
            cpp = ctx.source(cpp_path)
            if tree is None or cpp is None:
                continue
            c_fields = parse_c_struct(cpp, c_name)
            py_fields = _ctypes_fields(tree, py_name)
            if c_fields is None or py_fields is None:
                findings.append(Finding(
                    self.name, py_path, 1,
                    f"{c_name} (C) or {py_name} (ctypes) struct not "
                    "found — the hop-anatomy ring mirror is gone"))
                continue
            if [(n, t) for n, t in c_fields] != \
                    [(n, t) for n, t in py_fields]:
                findings.append(Finding(
                    self.name, py_path, 1,
                    f"{c_name} layout drifted: C has {c_fields}, "
                    f"ctypes mirror has {py_fields}"))
            size = sum(_SIZES.get(t, 0) for _n, t in c_fields)
            m = re.search(r"sizeof\(%s\)\s*==\s*(\d+)" % c_name, cpp)
            asserted = int(m.group(1)) if m else None
            if asserted is not None and size != asserted:
                findings.append(Finding(
                    self.name, py_path, 1,
                    f"{c_name} packs to {size} bytes but {cpp_path} "
                    f"asserts {asserted}"))
        return findings

    # -- FrameStatus reason enum ------------------------------------------
    def _check_reason_enum(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        tree = ctx.tree(FRAMES_PY)
        cpp = ctx.source(TCPPS_CPP)
        if tree is None or cpp is None:
            return findings
        enum = parse_c_enum(cpp, "FrameStatus")
        reasons = _dict_literal(tree, "BATCH_REASONS")
        if enum is None or reasons is None:
            findings.append(Finding(
                self.name, FRAMES_PY, 1,
                "FrameStatus enum or BATCH_REASONS map not found — "
                "the reason-code bridge is gone"))
            return findings
        c_map = {code: name[len("FRAME_"):].lower()
                 for code, name in enum.items() if code != 0}
        for code in sorted(set(c_map) | set(reasons)):
            c_name = c_map.get(code)
            py_name = reasons.get(code)
            if c_name != py_name:
                findings.append(Finding(
                    self.name, FRAMES_PY, 1,
                    f"frame-rejection reason {code}: C says "
                    f"{c_name!r}, BATCH_REASONS says {py_name!r}"))
        return findings
