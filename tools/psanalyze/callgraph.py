"""Static call graph over ``pytorch_ps_mpi_tpu/`` + thread-root and
native-site discovery — the substrate of the ``thread-affinity`` rule.

Resolution is deliberately conservative (names resolve within the
defining module/class first, then by project-unique simple name): a
missed edge costs a missed finding, a spurious edge costs a false
positive in the default test path, and the second is the expensive one.
The rule's job is the invariant PRs 3–10 re-asserted by hand — "no
thread but the serve loop touches a native transport handle" — so the
graph only needs to be faithful around thread entry points and ctypes
call sites, both of which are syntactically distinctive:

- **native sites**: any ``X.wc_*`` / ``X.tps_*`` / ``X.psq_*`` call —
  the ctypes-bound symbol prefixes of the three native libraries;
- **thread roots**: resolved ``threading.Thread(target=...)`` targets,
  ``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses, and every
  callable handed to ``MetricsHTTPServer`` (render + routes — those run
  on the HTTP server's per-request threads).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.psanalyze.core import AnalysisContext

NATIVE_PREFIXES = ("wc_", "tps_", "psq_")


@dataclass
class FunctionInfo:
    """One function/method definition and what its body references."""

    qname: str          # "<relpath>::Class.method" / "<relpath>::func"
    path: str
    line: int
    cls: Optional[str]  # enclosing class name, if a method
    simple: str         # unqualified def name
    calls: List[Tuple[str, Optional[str], int]] = field(
        default_factory=list)  # (kind, name, line): kind in
    # {"name", "self", "attr"}; name is the called simple name
    native_calls: List[Tuple[str, int]] = field(default_factory=list)


@dataclass
class ThreadRoot:
    qname: str
    reason: str  # "thread-target" / "http-handler" / "http-route"
    path: str
    line: int


class CallGraph:
    """defs, edges, native sites and thread roots for one tree."""

    def __init__(self) -> None:
        self.defs: Dict[str, FunctionInfo] = {}
        self.by_simple: Dict[str, List[str]] = {}
        self.edges: Dict[str, Set[str]] = {}
        self.roots: List[ThreadRoot] = []

    # -- queries ----------------------------------------------------------
    def reachable_native(
        self, start: str
    ) -> Optional[Tuple[List[str], Tuple[str, int]]]:
        """BFS from ``start``: the first path reaching a native call
        site, as ``(qname chain, (native symbol, line))`` — or None."""
        seen = {start}
        queue: List[Tuple[str, List[str]]] = [(start, [start])]
        while queue:
            cur, chain = queue.pop(0)
            info = self.defs.get(cur)
            if info is None:
                continue
            if info.native_calls:
                return chain, info.native_calls[0]
            for nxt in sorted(self.edges.get(cur, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append((nxt, chain + [nxt]))
        return None


def _module_name(rel: str) -> str:
    return rel[:-3].replace("/", ".")


class _ModuleVisitor(ast.NodeVisitor):
    """Collect defs + per-function call references for one module."""

    def __init__(self, graph: CallGraph, rel: str):
        self.graph = graph
        self.rel = rel
        self._class_stack: List[str] = []
        self._func_stack: List[FunctionInfo] = []
        # name -> dotted module/import target (for Thread resolution)
        self.imports: Dict[str, str] = {}
        self.http_handler_classes: List[str] = []
        # (expr node, line) callables handed to MetricsHTTPServer
        self.http_route_callables: List[Tuple[ast.AST, int]] = []
        self.thread_targets: List[Tuple[ast.AST, int]] = []

    # -- defs -------------------------------------------------------------
    def _qualify(self, name: str) -> str:
        cls = ".".join(self._class_stack) if self._class_stack else None
        if self._func_stack:  # nested def: scope to the outer function
            return f"{self._func_stack[-1].qname}.<locals>.{name}"
        if cls:
            return f"{self.rel}::{cls}.{name}"
        return f"{self.rel}::{name}"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        base_names = {b.id if isinstance(b, ast.Name) else b.attr
                      for b in node.bases
                      if isinstance(b, (ast.Name, ast.Attribute))}
        if "BaseHTTPRequestHandler" in base_names:
            self.http_handler_classes.append(node.name)
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node) -> None:
        info = FunctionInfo(
            qname=self._qualify(node.name), path=self.rel,
            line=node.lineno,
            cls=".".join(self._class_stack) or None,
            simple=node.name)
        self.graph.defs[info.qname] = info
        self.graph.by_simple.setdefault(node.name, []).append(info.qname)
        self._func_stack.append(info)
        # method bodies inside a class should not inherit the class
        # qualifier for their OWN nested defs' class attribution
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambda bodies contribute their calls to the enclosing function
        self.generic_visit(node)

    # -- imports ----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.imports[alias.asname or alias.name.split(".")[0]] = \
                alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            self.imports[alias.asname or alias.name] = \
                f"{node.module}.{alias.name}" if node.module else alias.name

    # -- calls ------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        cur = self._func_stack[-1] if self._func_stack else None
        if isinstance(func, ast.Attribute):
            if func.attr.startswith(NATIVE_PREFIXES):
                if cur is not None:
                    cur.native_calls.append((func.attr, node.lineno))
            elif cur is not None:
                if (isinstance(func.value, ast.Name)
                        and func.value.id == "self"):
                    cur.calls.append(("self", func.attr, node.lineno))
                else:
                    cur.calls.append(("attr", func.attr, node.lineno))
            callee = func.attr
        elif isinstance(func, ast.Name):
            if cur is not None:
                cur.calls.append(("name", func.id, node.lineno))
            callee = func.id
        else:
            callee = None
        # thread roots: Thread(target=...), MetricsHTTPServer(...), and
        # callbacks registered onto the scrape path (collectors run at
        # render time on the HTTP server's request threads)
        if callee == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self.thread_targets.append((kw.value, node.lineno))
        elif callee in ("MetricsHTTPServer", "add_route",
                        "add_collector"):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self.http_route_callables.append((arg, node.lineno))
        self.generic_visit(node)


def _resolve_callable_expr(
    visitor: _ModuleVisitor, graph: CallGraph, expr: ast.AST,
    enclosing_cls: Optional[str],
) -> List[str]:
    """qnames a callable expression may refer to: a Name (local def /
    nested def), ``self.X`` (method of the enclosing class), a lambda
    (its body's calls are attributed to the enclosing function already),
    or a dict literal of routes (each value resolved)."""
    rel = visitor.rel
    out: List[str] = []
    if isinstance(expr, ast.Dict):
        for v in expr.values:
            out.extend(_resolve_callable_expr(visitor, graph, v,
                                              enclosing_cls))
        return out
    if isinstance(expr, ast.Lambda):
        # a route lambda's body: resolve every call it makes
        for sub in ast.walk(expr.body):
            if isinstance(sub, ast.Call):
                out.extend(_resolve_callable_expr(
                    visitor, graph, sub.func, enclosing_cls))
        return out
    if isinstance(expr, ast.Name):
        for q in graph.by_simple.get(expr.id, ()):
            info = graph.defs[q]
            if info.path == rel:
                out.append(q)
        return out
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id == "self" and enclosing_cls:
            q = f"{rel}::{enclosing_cls}.{expr.attr}"
            if q in graph.defs:
                out.append(q)
                return out
        # fall back: project-unique method name
        cands = graph.by_simple.get(expr.attr, [])
        if len(cands) == 1:
            out.append(cands[0])
    return out


def build_callgraph(ctx: AnalysisContext,
                    package: str = "pytorch_ps_mpi_tpu") -> CallGraph:
    graph = CallGraph()
    visitors: List[_ModuleVisitor] = []
    for rel in ctx.py_files(under=(package,)):
        tree = ctx.tree(rel)
        if tree is None:
            continue
        v = _ModuleVisitor(graph, rel)
        v.visit(tree)
        visitors.append(v)

    # -- edges (after all defs are known) ---------------------------------
    for info in graph.defs.values():
        edges = graph.edges.setdefault(info.qname, set())
        for kind, name, _line in info.calls:
            if name is None:
                continue
            targets: List[str] = []
            if kind == "self" and info.cls is not None:
                q = f"{info.path}::{info.cls}.{name}"
                if q in graph.defs:
                    targets = [q]
            if not targets and kind in ("name",):
                # local module function (or nested def in this function)
                nested = f"{info.qname}.<locals>.{name}"
                if nested in graph.defs:
                    targets = [nested]
                else:
                    local = f"{info.path}::{name}"
                    if local in graph.defs:
                        targets = [local]
            if not targets:
                # project-unique simple name — the conservative
                # cross-module fallback
                cands = graph.by_simple.get(name, [])
                if len(cands) == 1:
                    targets = cands
            edges.update(targets)

    # -- thread roots -----------------------------------------------------
    for v in visitors:
        for cls in v.http_handler_classes:
            for q, info in graph.defs.items():
                if (info.path == v.rel and info.cls is not None
                        and info.cls.split(".")[-1] == cls
                        and info.simple.startswith("do_")):
                    graph.roots.append(ThreadRoot(
                        q, "http-handler", info.path, info.line))
        for expr, line in v.thread_targets:
            cls = _enclosing_class_of_line(graph, v.rel, line)
            for q in _resolve_callable_expr(v, graph, expr, cls):
                graph.roots.append(ThreadRoot(
                    q, "thread-target", v.rel, line))
        for expr, line in v.http_route_callables:
            cls = _enclosing_class_of_line(graph, v.rel, line)
            for q in _resolve_callable_expr(v, graph, expr, cls):
                graph.roots.append(ThreadRoot(
                    q, "http-route", v.rel, line))
    return graph


def _enclosing_class_of_line(graph: CallGraph, rel: str,
                             line: int) -> Optional[str]:
    """The class of the method whose def most closely precedes ``line``
    in ``rel`` — good enough to resolve ``self.X`` route references."""
    best: Optional[FunctionInfo] = None
    for info in graph.defs.values():
        if info.path != rel or info.cls is None or info.line > line:
            continue
        if best is None or info.line > best.line:
            best = info
    return best.cls if best is not None else None
