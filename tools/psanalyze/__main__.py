"""CLI: ``python -m tools.psanalyze [--root DIR] [--json] [--rules ...]``.

Exit code 0 when the tree is clean, 1 when any rule fired (pragma-
suppressed findings do not fail the run but are counted in the output),
2 on usage errors. ``make analyze`` runs this in the default test path.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from tools.psanalyze.core import (
        all_rules,
        render_human,
        render_json,
        run_analysis,
    )

    ap = argparse.ArgumentParser(
        prog="psanalyze",
        description="repo-native static analysis for the PS stack")
    ap.add_argument("--root", default=None,
                    help="tree to analyze (default: this repo)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="list available rules and exit")
    args = ap.parse_args(argv)

    if args.list:
        for rule in all_rules():
            print(f"{rule.name:18s} {rule.description}")
        return 0
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    names = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        res = run_analysis(root, names)
    except KeyError as e:
        print(f"psanalyze: {e.args[0]}", file=sys.stderr)
        return 2
    print(render_json(res) if args.json else render_human(res))
    return res.exit_code


if __name__ == "__main__":
    sys.exit(main())
