"""psanalyze engine: Finding/Rule model, file+AST cache, pragmas, runner.

Everything here is analysis-time only — the tool imports nothing from
``pytorch_ps_mpi_tpu`` (it must run, and fail loudly, even when the
package itself is broken enough not to import). Rules read source
through :class:`AnalysisContext`, which walks a *root* directory —
normally the repo, a seeded-defect temp copy in ``tools/analyze_smoke``.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: directories (relative to root) a rule may ask the context to walk
PY_DIRS = ("pytorch_ps_mpi_tpu", "examples", "benchmarks", "tools")

#: ``# psanalyze: ok <rule>[, <rule>...]`` on the flagged line or the
#: line directly above it suppresses the named rules' findings there
_PRAGMA = re.compile(r"#\s*psanalyze:\s*ok\s+([a-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file:line under the root."""

    rule: str
    path: str  # root-relative, forward slashes
    line: int
    message: str

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Rule:
    """Base rule: subclasses set ``name``/``description`` and implement
    :meth:`run` returning findings (pragma filtering is the runner's
    job, not the rule's)."""

    name: str = ""
    description: str = ""

    def run(self, ctx: "AnalysisContext") -> List[Finding]:
        raise NotImplementedError


class AnalysisContext:
    """Cached source/AST access for one analysis root.

    Files are read lazily and parsed at most once; a rule asking for a
    missing file gets ``None`` (rules degrade to "surface absent"
    findings or silence, never crashes — the smoke seeds partial trees).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._source: Dict[str, Optional[str]] = {}
        self._tree: Dict[str, Optional[ast.Module]] = {}
        self._py_files: Optional[List[str]] = None

    # -- files ------------------------------------------------------------
    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def exists(self, rel: str) -> bool:
        return os.path.isfile(self.abspath(rel))

    def source(self, rel: str) -> Optional[str]:
        if rel not in self._source:
            try:
                with open(self.abspath(rel), encoding="utf-8",
                          errors="replace") as f:
                    self._source[rel] = f.read()
            except OSError:
                self._source[rel] = None
        return self._source[rel]

    def lines(self, rel: str) -> List[str]:
        src = self.source(rel)
        return src.splitlines() if src is not None else []

    def tree(self, rel: str) -> Optional[ast.Module]:
        if rel not in self._tree:
            src = self.source(rel)
            try:
                self._tree[rel] = ast.parse(src) if src is not None else None
            except SyntaxError:
                self._tree[rel] = None
        return self._tree[rel]

    def py_files(self, under: Sequence[str] = PY_DIRS) -> List[str]:
        """Root-relative paths of every ``.py`` file under the given
        top-level directories (sorted, ``__pycache__`` skipped)."""
        out = []
        for top in under:
            base = self.abspath(top)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, fn),
                                              self.root)
                        out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    # -- pragmas ----------------------------------------------------------
    def suppressed(self, f: Finding) -> bool:
        """True when the flagged line carries a ``# psanalyze: ok
        <rule>`` pragma naming ``f.rule``, or the line directly above
        is a comment-only pragma line (a trailing pragma on code never
        spills onto the next line)."""
        lines = self.lines(f.path)

        def match(text: str) -> bool:
            m = _PRAGMA.search(text)
            return bool(m and f.rule in
                        {r.strip() for r in m.group(1).split(",")})

        if 1 <= f.line <= len(lines) and match(lines[f.line - 1]):
            return True
        above = lines[f.line - 2] if 2 <= f.line <= len(lines) + 1 else ""
        return above.strip().startswith("#") and match(above)


@dataclass
class AnalysisResult:
    root: str
    rules: List[str]
    findings: List[Finding]
    suppressed: List[Finding] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self) -> Dict[str, object]:
        return {
            "root": self.root,
            "rules": self.rules,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "finding_count": len(self.findings),
            "suppressed_count": len(self.suppressed),
        }


def all_rules() -> List[Rule]:
    from tools.psanalyze.rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def run_analysis(root: str,
                 rule_names: Optional[Iterable[str]] = None
                 ) -> AnalysisResult:
    """Run the selected rules (default: all) against ``root`` and split
    findings into live vs pragma-suppressed."""
    ctx = AnalysisContext(root)
    rules = all_rules()
    if rule_names is not None:
        wanted = set(rule_names)
        unknown = wanted - {r.name for r in rules}
        if unknown:
            raise KeyError(
                f"unknown rule(s) {sorted(unknown)}; "
                f"have {sorted(r.name for r in rules)}")
        rules = [r for r in rules if r.name in wanted]
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in rules:
        for f in rule.run(ctx):
            (suppressed if ctx.suppressed(f) else findings).append(f)
    findings.sort(key=lambda f: (f.rule, f.path, f.line))
    suppressed.sort(key=lambda f: (f.rule, f.path, f.line))
    return AnalysisResult(root=ctx.root,
                          rules=[r.name for r in rules],
                          findings=findings, suppressed=suppressed)


def render_human(res: AnalysisResult) -> str:
    lines = []
    for f in res.findings:
        lines.append(f.render())
    lines.append(
        f"psanalyze: {len(res.findings)} finding(s), "
        f"{len(res.suppressed)} suppressed, "
        f"rules: {', '.join(res.rules)}")
    return "\n".join(lines)


def render_json(res: AnalysisResult) -> str:
    return json.dumps(res.to_dict(), indent=2, sort_keys=True)


# -- shared AST helpers (used by several rules) -----------------------------

def const_str(node: ast.AST) -> Optional[str]:
    """The literal string a node holds, or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """A tuple/list literal of string constants, or None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = const_str(el)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for nested Name/Attribute chains, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
