"""Telemetry overhead smoke: recorder-on vs recorder-off step time.

``make telemetry-smoke`` runs this: a short CPU trainer (MLP, synthetic
data) with single steps alternating recorder OFF and ON, a
FlightRecorder JSONL + per-phase report generated from the ON steps,
and a hard failure when the enabled recorder costs more than
``--threshold`` (default 5%) of the disabled step time — the
zero-cost-when-disabled contract, plus a bound on the enabled cost.

Statistics: per-step alternation means both modes sample the same load
profile, and MEDIANS are compared — this 1-core container shows 10x
scheduler stalls on individual ms-scale steps, which poison any
mean-based statistic, while a persistent regression (an accidentally-hot
code path in the disabled guard, a lock on the step path) shifts every
sample and still fails the gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from pytorch_ps_mpi_tpu import MPI_PS, telemetry
from pytorch_ps_mpi_tpu.models import MLP
from pytorch_ps_mpi_tpu.trainer import Trainer


def build_trainer(batch: int = 256, numerics: bool = False):
    model = MLP(features=(128, 10))
    key = jax.random.key(0)
    x0 = jnp.zeros((batch, 64), jnp.float32)
    params = model.init(key, x0)

    def batches():
        k = key
        while True:
            k, kk = jax.random.split(k)
            x = jax.random.normal(kk, (batch, 64))
            y = jax.random.randint(jax.random.fold_in(kk, 1), (batch,), 0, 10)
            yield x, y

    def loss_fn(p, b):
        x, y = b
        logp = jax.nn.log_softmax(model.apply(p, x))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    opt = MPI_PS(params, optim="sgd", lr=0.05, average=True,
                 numerics=numerics)
    return Trainer(opt, loss_fn), batches()


def timed_step(trainer: Trainer, data) -> float:
    t0 = time.perf_counter()
    trainer.fit(data, 1)
    return time.perf_counter() - t0


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=40,
                    help="measured trainer steps PER MODE, alternated "
                         "step-by-step")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="max allowed recorder overhead fraction")
    ap.add_argument("--out", default="/tmp/telemetry_smoke",
                    help="directory for the JSONL + report artifacts")
    ap.add_argument("--numerics", action="store_true",
                    help="run the trainer with MPI_PS(numerics=True) — "
                         "the fused grad-norm/NaN/update-ratio stats in "
                         "every step — and hold it to the SAME <=5% "
                         "recorder-overhead budget")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    trainer, data = build_trainer(numerics=args.numerics)
    trainer.fit(data, 3)  # compile warmup, outside every measurement

    off, on = [], []
    # ONE recorder across every ON step (install/disable pause+resume
    # the same buffer), so the JSONL covers all instrumented steps
    rec = telemetry.FlightRecorder(capacity=65536, worker="smoke")
    for _ in range(args.steps):  # per-step alternation: same load profile
        telemetry.disable()
        off.append(timed_step(trainer, data))
        telemetry.install(rec)
        on.append(timed_step(trainer, data))
    jsonl = rec.dump_jsonl(os.path.join(args.out, "smoke.jsonl"))
    telemetry.disable()

    from tools.telemetry_report import format_table, summarize

    report = format_table(summarize([jsonl]))
    with open(os.path.join(args.out, "report.txt"), "w") as f:
        f.write(report + "\n")
    print(report)

    base, instrumented = _median(off), _median(on)
    overhead = (instrumented - base) / base
    verdict = {
        "step_ms_disabled": round(base * 1e3, 4),
        "step_ms_enabled": round(instrumented * 1e3, 4),
        "overhead_frac": round(overhead, 4),
        "threshold": args.threshold,
        "events_recorded": len(rec),
        "artifacts": [jsonl, os.path.join(args.out, "report.txt")],
    }
    print(json.dumps(verdict))
    if len(rec) == 0:
        print("FAIL: recorder captured no events while enabled")
        return 1
    if overhead > args.threshold:
        print(f"FAIL: recorder overhead {overhead:.1%} exceeds "
              f"{args.threshold:.0%}")
        return 1
    print(f"OK: recorder overhead {overhead:.1%} within "
          f"{args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
